"""System connector: the engine's own state as SQL tables.

The ``system.runtime`` role (reference: connector/system/
SystemConnector.java + RuntimeQueriesSystemTable / RuntimeTasksSystemTable,
and the JMX connector for counters): the engine dogfoods its own scan path
— rows come from the process-wide telemetry registries
(telemetry/runtime.py) and the metrics snapshot (telemetry/metrics.py),
served through the ordinary Connector SPI so every planner/executor layer
treats them like any other table.

Tables are schema-qualified (``runtime.queries`` etc.);
``Catalog.resolve_table`` resolves ``system.runtime.queries`` by trying the
schema-qualified name against this connector first.

Cookbook:
    SELECT query_id, state FROM system.runtime.queries
    SELECT worker, count(*) FROM system.runtime.tasks GROUP BY worker
    SELECT * FROM system.metrics.counters WHERE name LIKE 'trino_scan%'
"""

from __future__ import annotations

import weakref
from typing import Optional, Sequence

from ..spi.batch import Column, ColumnBatch
from ..spi.connector import (
    ColumnSchema,
    Connector,
    ConnectorPageSource,
    Split,
    TableSchema,
)
from ..spi.types import BIGINT, DOUBLE, VARCHAR

__all__ = ["SystemConnector"]


def _schema(name: str, cols: list[tuple]) -> TableSchema:
    return TableSchema(name, tuple(ColumnSchema(n, t) for n, t in cols))


_TABLES = {
    "runtime.queries": _schema("runtime.queries", [
        ("query_id", VARCHAR), ("state", VARCHAR), ("user", VARCHAR),
        ("sql", VARCHAR), ("wall_ms", DOUBLE), ("cpu_ms", DOUBLE),
        ("output_rows", BIGINT), ("input_rows", BIGINT),
        ("input_bytes", BIGINT), ("retry_count", BIGINT),
        ("peak_memory_bytes", BIGINT), ("error", VARCHAR),
        ("queued_time_ms", DOUBLE), ("resource_group", VARCHAR),
        ("adaptive_decisions", VARCHAR),
    ]),
    "runtime.resource_groups": _schema("runtime.resource_groups", [
        ("path", VARCHAR), ("policy", VARCHAR), ("weight", BIGINT),
        ("soft_concurrency_limit", BIGINT),
        ("hard_concurrency_limit", BIGINT), ("max_queued", BIGINT),
        ("running", BIGINT), ("queued", BIGINT),
        ("memory_bytes", BIGINT), ("cpu_usage_s", DOUBLE),
    ]),
    # durable flight-recorder feed (telemetry/journal.py): completed queries
    # read back from the on-disk journal, surviving coordinator restarts
    "runtime.query_history": _schema("runtime.query_history", [
        ("query_id", VARCHAR), ("state", VARCHAR), ("user", VARCHAR),
        ("sql", VARCHAR), ("fingerprint", VARCHAR), ("ts", DOUBLE),
        ("wall_ms", DOUBLE), ("cpu_ms", DOUBLE),
        ("output_rows", BIGINT), ("input_rows", BIGINT),
        ("input_bytes", BIGINT), ("retry_count", BIGINT),
        ("peak_memory_bytes", BIGINT), ("queued_time_ms", DOUBLE),
        ("resource_group", VARCHAR), ("speculative_wins", BIGINT),
        ("error", VARCHAR), ("error_code", VARCHAR),
    ]),
    "runtime.tasks": _schema("runtime.tasks", [
        ("query_id", VARCHAR), ("task_id", VARCHAR), ("fragment", BIGINT),
        ("task_index", BIGINT), ("worker", VARCHAR), ("state", VARCHAR),
        ("wall_ms", DOUBLE), ("error", VARCHAR),
    ]),
    "runtime.workers": _schema("runtime.workers", [
        ("worker", VARCHAR), ("state", VARCHAR),
        ("blacklist_score", DOUBLE), ("running_tasks", BIGINT),
        ("queued_tasks", BIGINT), ("last_heartbeat_age_ms", DOUBLE),
    ]),
    # HA coordinator fleet (execution/ha.py lease directory); with HA off
    # this is the single local coordinator
    "runtime.coordinators": _schema("runtime.coordinators", [
        ("coordinator", VARCHAR), ("state", VARCHAR),
        ("lease_age_ms", DOUBLE), ("in_flight_queries", BIGINT),
        ("url", VARCHAR),
    ]),
    "metrics.counters": _schema("metrics.counters", [
        ("name", VARCHAR), ("kind", VARCHAR), ("value", DOUBLE),
    ]),
    # three-tier cache plane (caching/): one row per plan/result tier and
    # per registered executable memo
    "runtime.caches": _schema("runtime.caches", [
        ("tier", VARCHAR), ("name", VARCHAR), ("entries", BIGINT),
        ("bytes", BIGINT), ("hits", BIGINT), ("misses", BIGINT),
        ("evictions", BIGINT), ("invalidations", BIGINT),
    ]),
}


class _OneBatchSource(ConnectorPageSource):
    def __init__(self, batch: ColumnBatch):
        self._batch = batch
        self._done = False

    def get_next_batch(self) -> Optional[ColumnBatch]:
        if self._done:
            return None
        self._done = True
        return self._batch

    def is_finished(self) -> bool:
        return self._done


class SystemConnector(Connector):
    name = "system"

    def __init__(self):
        self._runner = None  # weakref to an attached runner (optional)

    def attach(self, runner) -> None:
        """Bind a runner so dispatcher-tracked state (execution/control.py
        DispatchManager) augments the process registries."""
        self._runner = weakref.ref(runner)

    # --- metadata ---------------------------------------------------------
    def list_tables(self) -> list[str]:
        return sorted(_TABLES)

    def get_table_schema(self, table: str) -> TableSchema:
        if table not in _TABLES:
            raise KeyError(f"no such system table: {table!r}")
        return _TABLES[table]

    # --- reads ------------------------------------------------------------
    def get_splits(self, table: str, splits_per_node: int,
                   node_count: int) -> list[Split]:
        self.get_table_schema(table)  # KeyError on unknown tables
        return [Split("system", table, None)]

    def create_page_source(self, split: Split, columns: Sequence[str],
                           constraint=None) -> ConnectorPageSource:
        rows = self._rows(split.table)
        schema = _TABLES[split.table]
        by_name = {c.name: (i, c.type) for i, c in enumerate(schema.columns)}
        cols = []
        for name in columns:
            idx, typ = by_name[name]
            cols.append(Column.from_values(typ, [r[idx] for r in rows]))
        return _OneBatchSource(ColumnBatch(list(columns), cols))

    def _rows(self, table: str) -> list[tuple]:
        from ..telemetry import metrics, runtime

        if table == "runtime.queries":
            out = [
                (q.query_id, q.state, q.user, q.sql, q.wall_ms, q.cpu_ms,
                 q.output_rows, q.input_rows, q.input_bytes, q.retry_count,
                 q.peak_memory_bytes, q.error, q.queued_ms, q.resource_group,
                 q.adaptive_decisions)
                for q in runtime.queries()
            ]
            # dispatcher-tracked queries (control.py FSM) that predate or
            # bypass run_with_query_events show up with their FSM state
            runner = self._runner() if self._runner is not None else None
            dispatcher = getattr(runner, "dispatcher", None)
            if dispatcher is not None:
                seen = {r[0] for r in out}
                for info in dispatcher.queries():
                    if info.query_id not in seen:
                        out.append((info.query_id, info.state, "", info.sql,
                                    0.0, 0.0, -1, 0, 0, 0, 0, None, 0.0,
                                    info.resource_group, ""))
            return out
        if table == "runtime.resource_groups":
            runner = self._runner() if self._runner is not None else None
            dispatcher = getattr(runner, "dispatcher", None)
            if dispatcher is None:
                return []
            return [
                (g.name, g.scheduling_policy, g.weight,
                 g.soft_concurrency_limit
                 if g.soft_concurrency_limit is not None
                 else g.hard_concurrency_limit,
                 g.hard_concurrency_limit, g.max_queued,
                 g.running, g.queued, g.memory_usage_bytes, g.cpu_usage_s)
                for g in dispatcher.groups()
            ]
        if table == "runtime.query_history":
            from ..telemetry import journal as tj

            return [
                (r.get("query_id", ""), r.get("state", ""),
                 r.get("user", ""), r.get("sql", ""),
                 r.get("fingerprint", ""), float(r.get("ts", 0.0) or 0.0),
                 float(r.get("wall_ms", 0.0) or 0.0),
                 float(r.get("cpu_ms", 0.0) or 0.0),
                 int(r.get("output_rows", -1) or 0),
                 int(r.get("input_rows", 0) or 0),
                 int(r.get("input_bytes", 0) or 0),
                 int(r.get("retry_count", 0) or 0),
                 int(r.get("peak_memory_bytes", 0) or 0),
                 float(r.get("queued_time_ms", 0.0) or 0.0),
                 r.get("resource_group", ""),
                 int(r.get("speculative_wins", 0) or 0),
                 r.get("error"), r.get("error_code"))
                for r in tj.history()
            ]
        if table == "runtime.tasks":
            return [
                (t.query_id, t.task_id, t.fragment, t.task_index, t.worker,
                 t.state, t.wall_ms, t.error)
                for t in runtime.tasks()
            ]
        if table == "runtime.workers":
            return self._worker_rows()
        if table == "runtime.coordinators":
            return self._coordinator_rows()
        if table == "runtime.caches":
            from .. import caching

            return [
                (r["tier"], r["name"], int(r["entries"]), int(r["bytes"]),
                 int(r["hits"]), int(r["misses"]), int(r["evictions"]),
                 int(r["invalidations"]))
                for r in caching.cache_rows(per_exec_cache=True)
            ]
        if table == "metrics.counters":
            out = []
            for name, snap in metrics.REGISTRY.snapshot().items():
                kind = snap["kind"]
                if kind == "distribution":
                    # flatten: scalar summary rows per distribution
                    for suffix, v in (("count", snap["count"]),
                                      ("sum", snap["sum"]),
                                      ("p50", snap["p50"]),
                                      ("p90", snap["p90"]),
                                      ("p99", snap["p99"])):
                        out.append((f"{name}_{suffix}", kind, float(v)))
                else:
                    out.append((name, kind, float(snap["value"])))
            return out
        raise KeyError(f"no such system table: {table!r}")

    def _coordinator_rows(self) -> list[tuple]:
        """The coordinator fleet from the HA lease directory.  With HA off
        (or no fleet registered yet) the single local coordinator is
        synthesized so the table is never empty mid-query."""
        from ..execution import ha

        rows = []
        if ha.ha_enabled() and ha.ha_dir():
            for m in ha.read_members():
                rows.append((m.node_id, m.state, m.age_s * 1000.0,
                             m.in_flight, m.url))
        if not rows:
            runner = self._runner() if self._runner is not None else None
            dispatcher = getattr(runner, "dispatcher", None)
            running = 0
            if dispatcher is not None:
                try:
                    running = sum(1 for i in dispatcher.queries()
                                  if i.state in ("QUEUED", "RUNNING"))
                except Exception:
                    running = 0
            rows.append((ha.node_id(), "ACTIVE", 0.0, running, ""))
        return rows

    def _worker_rows(self) -> list[tuple]:
        """Per-worker operational view: failure-detector state, cluster
        blacklist score, task counts, heartbeat age.  Process runners carry
        a WorkerFailureDetector (worker_rows feed); the in-process runner
        synthesizes from discovery (control.py NodeManager), where a drained
        slot reports SHUTTING_DOWN and a failed pinger reports GONE."""
        runner = self._runner() if self._runner is not None else None
        if runner is None:
            return []
        bl = getattr(runner, "cluster_blacklist", None)
        scores = bl.snapshot() if bl is not None else {}
        fd = getattr(runner, "failure_detector", None)
        if hasattr(fd, "worker_rows"):
            return [
                (r["worker"], r["state"], float(scores.get(r["worker"], 0.0)),
                 r["running_tasks"], r["queued_tasks"],
                 r["last_heartbeat_age_ms"])
                for r in fd.worker_rows()
            ]
        nodes = getattr(runner, "nodes", None)
        if nodes is None:
            return []
        import time as _time

        failed = set()
        try:
            failed = set(fd.failed_nodes())
        except Exception:
            pass
        now = _time.monotonic()
        out = []
        for n in nodes.all_nodes():
            if n.coordinator:
                continue
            state = ("GONE" if n.node_id in failed
                     else "SHUTTING_DOWN" if n.draining else "ACTIVE")
            out.append((n.node_id, state,
                        float(scores.get(n.node_id, 0.0)), 0, 0,
                        (now - n.last_heartbeat) * 1000.0))
        return out
