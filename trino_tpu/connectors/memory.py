"""In-memory table connector + /dev/null connector.

Mirror ``plugin/trino-memory`` (MemoryConnector — the v1 write target) and
``plugin/trino-blackhole`` (BlackHoleConnector — perf-test sink).  Tables live
as lists of ColumnBatches on the host; splits partition the batch list so
multi-split scans exercise the same paths as the generator connector.
"""

from __future__ import annotations

import threading
from typing import Any, Optional, Sequence

import numpy as np

from ..spi.batch import ColumnBatch
from ..spi.connector import (
    Connector,
    ConnectorPageSink,
    ConnectorPageSource,
    Split,
    TableSchema,
    TableStatistics,
)

__all__ = ["MemoryConnector", "BlackholeConnector"]


class _ListPageSource(ConnectorPageSource):
    def __init__(self, batches: list[ColumnBatch], columns: Sequence[str]):
        self._batches = batches
        self._columns = list(columns)
        self._i = 0

    def get_next_batch(self) -> Optional[ColumnBatch]:
        if self._i >= len(self._batches):
            return None
        b = self._batches[self._i]
        self._i += 1
        return b.select(self._columns)

    def is_finished(self) -> bool:
        return self._i >= len(self._batches)


def _batch_overlaps(b: ColumnBatch, constraint) -> bool:
    """Min/max zone-map check: can any row of the host batch satisfy the
    TupleDomain?  Device-pinned batches (live mask) always pass — pulling
    them down for stats would defeat the pinning.  Stats are computed once
    per batch and memoized on the batch object (the reference keeps
    per-page min/max in connector metadata, e.g. ORC stripe stats)."""
    if b.live is not None:
        return True
    stats = getattr(b, "_domain_stats", None)
    if stats is None:
        stats = {}
        b._domain_stats = stats
    missing = [n for n in constraint.domains
               if n not in stats and n in b.names]
    if missing:
        for name in missing:
            c = b.columns[b.names.index(name)]
            data = np.asarray(c.data)
            valid = None if c.valid is None else np.asarray(c.valid)
            has_null = bool((~valid).any()) if valid is not None else False
            if c.dictionary is not None:
                present = data if valid is None else data[valid]
                if present.size:
                    vals = c.dictionary[np.unique(present)]
                    # long-decimal dictionaries hold python ints: zone-map
                    # bounds must stay in storage space, not stringify
                    if isinstance(vals[0], int):
                        stats[name] = (int(vals[0]), int(vals[-1]), has_null)
                    else:
                        stats[name] = (str(vals[0]), str(vals[-1]), has_null)
                else:
                    stats[name] = (None, None, has_null)
            elif np.issubdtype(data.dtype, np.number) or data.dtype == bool:
                present = data if valid is None else data[valid]
                if present.size:
                    mn, mx = present.min(), present.max()
                    if isinstance(mn, np.floating) and (
                            np.isnan(mn) or np.isnan(mx)):
                        continue  # NaNs poison comparisons: no stats
                    stats[name] = (mn.item(), mx.item(), has_null)
                else:
                    stats[name] = (None, None, has_null)
    mins = {k: v[0] for k, v in stats.items()}
    maxs = {k: v[1] for k, v in stats.items()}
    nulls = {k: v[2] for k, v in stats.items()}
    return constraint.overlaps_stats(mins, maxs, nulls)


class _MemoryPageSink(ConnectorPageSink):
    def __init__(self, connector: "MemoryConnector", table: str):
        self._connector = connector
        self._table = table
        self._staged: list[ColumnBatch] = []

    def append(self, batch: ColumnBatch) -> bool:
        self._staged.append(batch)
        return True

    def finish(self) -> list[Any]:
        return [self._staged]


class MemoryConnector(Connector):
    name = "memory"

    def __init__(self):
        self._lock = threading.Lock()
        self._schemas: dict[str, TableSchema] = {}
        self._data: dict[str, list[ColumnBatch]] = {}
        # live-row counts of device-pinned tables (padding rows excluded;
        # computed once at pin time to avoid per-query device syncs)
        self._pinned_rows: dict[str, int] = {}
        # observability: batches skipped by TupleDomain min/max pruning
        self.batches_pruned = 0
        # data_version tokens: drawn from one instance-wide monotonic
        # counter so a drop/recreate cycle can never reissue an old token
        # (a reset-to-zero per-table counter would let a result cached
        # against the ORIGINAL table at v0 be served for the NEW one)
        self._versions: dict[str, int] = {}
        self._next_version = 0

    def _bump_version(self, table: str) -> None:
        # callers hold self._lock
        self._next_version += 1
        self._versions[table] = self._next_version
        from ..caching import result_cache

        result_cache.invalidate_table(self.name, table)

    def data_version(self, table: str):
        with self._lock:
            if table not in self._schemas:
                raise KeyError(f"memory: no such table {table!r}")
            return self._versions.get(table, 0)

    def list_tables(self) -> list[str]:
        with self._lock:
            return sorted(self._schemas)

    def get_table_schema(self, table: str) -> TableSchema:
        with self._lock:
            if table not in self._schemas:
                raise KeyError(f"memory: no such table {table!r}")
            return self._schemas[table]

    def get_table_statistics(self, table: str) -> TableStatistics:
        analyzed = getattr(self, "_analyzed_stats", {}).get(table)
        if analyzed is not None:
            return analyzed
        with self._lock:
            if table in self._pinned_rows:
                rows = self._pinned_rows[table]
            else:
                rows = sum(b.num_rows for b in self._data.get(table, []))
        return TableStatistics(row_count=float(rows))

    def get_procedures(self) -> dict:
        """CALL memory.truncate_table('t') / memory.pin_table('t')
        (reference: spi/procedure/Procedure.java — connector-registered
        procedures dispatched by CallTask)."""

        def truncate_table(table: str) -> str:
            with self._lock:
                if table not in self._schemas:
                    raise KeyError(f"memory: no such table {table!r}")
                self._data[table] = []
                self._pinned_rows.pop(table, None)
                self._bump_version(table)
            return f"truncated {table}"

        def pin_table(table: str) -> str:
            self.pin_to_device(table)
            return f"pinned {table}"

        return {"truncate_table": truncate_table, "pin_table": pin_table}

    def create_table(self, schema: TableSchema) -> None:
        with self._lock:
            if schema.name in self._schemas:
                raise ValueError(f"memory: table {schema.name!r} already exists")
            self._schemas[schema.name] = schema
            self._data[schema.name] = []
            self._bump_version(schema.name)

    def drop_table(self, table: str) -> None:
        with self._lock:
            self._schemas.pop(table, None)
            self._data.pop(table, None)
            self._pinned_rows.pop(table, None)
            self._versions.pop(table, None)
            from ..caching import result_cache

            result_cache.invalidate_table(self.name, table)

    def get_splits(self, table: str, splits_per_node: int, node_count: int) -> list[Split]:
        with self._lock:
            n = len(self._data.get(table, []))
        want = max(1, splits_per_node * node_count)
        n_splits = min(want, max(n, 1))
        bounds = np.linspace(0, n, n_splits + 1, dtype=np.int64)
        return [
            Split("memory", table, (int(bounds[i]), int(bounds[i + 1])))
            for i in range(n_splits)
            if bounds[i + 1] > bounds[i] or n == 0 and i == 0
        ]

    def create_page_source(self, split: Split, columns: Sequence[str],
                           constraint=None) -> ConnectorPageSource:
        lo, hi = split.info
        with self._lock:
            batches = self._data[split.table][lo:hi]
        if constraint is not None and not constraint.is_all:
            kept = [b for b in batches
                    if _batch_overlaps(b, constraint)]
            self.batches_pruned += len(batches) - len(kept)
            batches = kept
        return _ListPageSource(batches, columns)

    def create_page_sink(self, table: str) -> ConnectorPageSink:
        self.get_table_schema(table)  # existence check
        return _MemoryPageSink(self, table)

    def finish_insert(self, table: str, fragments: list[Any]) -> None:
        with self._lock:
            for staged in fragments:
                self._data[table].extend(staged)
                if table in self._pinned_rows:
                    self._pinned_rows[table] += sum(
                        b.live_count for b in staged)
            self._bump_version(table)

    # ---- transactions ----------------------------------------------------
    def begin_transaction(self):
        """Snapshot handle: per-table batch-list lengths + the table set.
        Rollback undoes INSERT/CTAS/CREATE TABLE performed since BEGIN by
        truncating back to the snapshot (DELETE's drop-and-rewrite is not
        transactional — mirrors the reference memory connector, which only
        supports INSERT/CREATE in a transaction)."""
        with self._lock:
            return {
                "tables": set(self._schemas),
                "lengths": {t: len(b) for t, b in self._data.items()},
            }

    def commit_transaction(self, handle) -> None:
        pass  # writes applied eagerly; commit just drops the snapshot

    def rollback_transaction(self, handle) -> None:
        if handle is None:
            return
        with self._lock:
            for t in list(self._schemas):
                if t not in handle["tables"]:
                    self._schemas.pop(t, None)
                    self._data.pop(t, None)
                    self._pinned_rows.pop(t, None)
                    self._versions.pop(t, None)
            for t, n in handle["lengths"].items():
                if t in self._data and len(self._data[t]) > n:
                    removed = self._data[t][n:]
                    del self._data[t][n:]
                    if t in self._pinned_rows:
                        self._pinned_rows[t] -= sum(
                            b.live_count for b in removed)
                    self._bump_version(t)

    def pin_to_device(self, table: str) -> None:
        """Make a table device-resident: batches become bucket-padded jax
        arrays living in HBM, so scans hand columns straight to the jitted
        pipeline with no host->device upload per query.  The TPU-native
        equivalent of the reference keeping hot pages in worker heap
        (MemoryPagesStore) — here the 'heap' is device memory."""
        import jax
        import jax.numpy as jnp
        import numpy as _np

        from ..spi.batch import Column, ColumnBatch, round_up_pow2

        from ..spi.batch import pad_to_bucket

        with self._lock:
            batches = self._data.get(table, [])
            total_rows = 0
            pinned = []
            for b in batches:
                already_dev = (b.columns
                               and not isinstance(b.columns[0].data, _np.ndarray))
                if already_dev:
                    # born on device (device-side generation / jitted
                    # pipeline output): keep it — a compact() here would
                    # drag the whole table through the host tunnel
                    lv = b.live
                    if lv is None:
                        lv = jnp.ones(b.num_rows, jnp.bool_)
                    pinned.append(ColumnBatch(b.names, list(b.columns),
                                              jax.device_put(jnp.asarray(lv))))
                    total_rows += b.live_count
                    continue
                b = pad_to_bucket(b.compact())
                total_rows += b.live_count
                live = b.live
                if live is None:
                    # a live mask marks the batch device-pinned downstream
                    # (ScanOperator skips host work for it) — attach an
                    # all-ones mask even when no padding was needed
                    live = _np.ones(b.num_rows, _np.bool_)
                cols = [
                    Column(c.type, jax.device_put(jnp.asarray(c.data)),
                           None if c.valid is None
                           else jax.device_put(jnp.asarray(c.valid)),
                           c.dictionary)
                    for c in b.columns
                ]
                pinned.append(ColumnBatch(
                    b.names, cols, jax.device_put(jnp.asarray(live))))
            self._data[table] = pinned
            self._pinned_rows[table] = total_rows


class _NullSink(ConnectorPageSink):
    def __init__(self):
        self.rows = 0

    def append(self, batch: ColumnBatch) -> bool:
        self.rows += batch.num_rows
        return True

    def finish(self) -> list[Any]:
        return [self.rows]


class BlackholeConnector(Connector):
    name = "blackhole"

    def __init__(self):
        self._schemas: dict[str, TableSchema] = {}

    def list_tables(self) -> list[str]:
        return sorted(self._schemas)

    def get_table_schema(self, table: str) -> TableSchema:
        if table not in self._schemas:
            raise KeyError(f"blackhole: no such table {table!r}")
        return self._schemas[table]

    def create_table(self, schema: TableSchema) -> None:
        self._schemas[schema.name] = schema

    def drop_table(self, table: str) -> None:
        self._schemas.pop(table, None)

    def get_splits(self, table, splits_per_node, node_count):
        return []

    def create_page_sink(self, table: str) -> ConnectorPageSink:
        self.get_table_schema(table)
        return _NullSink()
