"""Catalog registry: name -> Connector instance.

Mirrors core/trino-main's catalog management (connector/StaticCatalogManager.
java + metadata resolution in metadata/MetadataManager) in miniature: a
session references one default catalog; qualified names pick others.
"""

from __future__ import annotations

from typing import Optional

from ..spi.connector import Connector, TableSchema

__all__ = ["Catalog", "default_catalog"]


class ViewDefinition:
    """A stored view: the defining query AST, plus (for materialized views)
    the backing table holding the last refresh (reference:
    spi/connector/ConnectorViewDefinition + MaterializedViewDefinition)."""

    __slots__ = ("query", "materialized", "backing")

    def __init__(self, query, materialized: bool = False, backing=None):
        self.query = query
        self.materialized = materialized
        self.backing = backing  # (catalog, table) of the refresh target


class Catalog:
    def __init__(self):
        self._connectors: dict[str, Connector] = {}
        # CREATE FUNCTION registry: name -> (params, return_type, body AST)
        # (reference: metadata/GlobalFunctionCatalog for SQL routines)
        self.sql_functions: dict[str, tuple] = {}
        # polymorphic table functions: name -> spi.table_function.TableFunction
        from ..spi.table_function import builtin_table_functions

        self.table_functions: dict = builtin_table_functions()
        # view registry: name -> ViewDefinition (reference:
        # metadata/MetadataManager view/materialized-view maps)
        self.views: dict = {}

    def register(self, name: str, connector: Connector) -> None:
        self._connectors[name] = connector

    def connector(self, name: str) -> Connector:
        if name not in self._connectors:
            raise KeyError(f"catalog not found: {name!r}")
        return self._connectors[name]

    def names(self) -> list[str]:
        return sorted(self._connectors)

    def resolve_table(self, name: str, default: str) -> tuple[str, str, TableSchema]:
        """'table', 'catalog.table' or 'catalog.schema.table' ->
        (catalog, table, schema).  Connectors with schema-qualified table
        names (connectors/system.py: 'runtime.queries') resolve the
        'schema.table' form first; the historical flat-namespace fallback
        ('catalog.x.t' -> table 't') is preserved for everything else."""
        parts = name.split(".")
        if len(parts) == 1:
            cat, table = default, parts[0]
        elif len(parts) == 2:
            cat, table = parts
            if cat not in self._connectors and default in self._connectors:
                # 'runtime.queries' under default_catalog='system': treat
                # the whole name as a schema-qualified table of the default
                try:
                    schema = self._connectors[default].get_table_schema(name)
                    return default, name, schema
                except KeyError:
                    pass
        else:
            cat = parts[0]
            qualified = ".".join(parts[1:])
            try:
                schema = self.connector(cat).get_table_schema(qualified)
                return cat, qualified, schema
            except KeyError:
                table = parts[-1]
        schema = self.connector(cat).get_table_schema(table)
        return cat, table, schema


def default_catalog(scale_factor: float = 0.01,
                    file_root: Optional[str] = None) -> Catalog:
    """Catalog with the standard engine-support connectors registered.

    ``file_root`` anchors the persistent file connector; default is a fresh
    temp directory per catalog, created lazily on first use."""
    from .file import FileConnector
    from .memory import BlackholeConnector, MemoryConnector
    from .system import SystemConnector
    from .tpch import TpchConnector

    cat = Catalog()
    cat.register("tpch", TpchConnector(scale_factor))
    cat.register("memory", MemoryConnector())
    cat.register("blackhole", BlackholeConnector())
    cat.register("file", FileConnector(file_root))
    cat.register("system", SystemConnector())
    return cat
