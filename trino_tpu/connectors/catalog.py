"""Catalog registry: name -> Connector instance.

Mirrors core/trino-main's catalog management (connector/StaticCatalogManager.
java + metadata resolution in metadata/MetadataManager) in miniature: a
session references one default catalog; qualified names pick others.
"""

from __future__ import annotations

import itertools
import threading
from typing import Optional

from ..spi.connector import Connector, TableSchema

__all__ = ["Catalog", "default_catalog"]


class ViewDefinition:
    """A stored view: the defining query AST, plus (for materialized views)
    the backing table holding the last refresh (reference:
    spi/connector/ConnectorViewDefinition + MaterializedViewDefinition).

    ``base_versions`` is the connector data_version vector of the base
    tables captured at refresh time — the same tokens the result cache
    keys on — so staleness is a pure token comparison
    (:meth:`Catalog.mv_is_stale`), no data inspection."""

    __slots__ = ("query", "materialized", "backing", "base_versions")

    def __init__(self, query, materialized: bool = False, backing=None,
                 base_versions=None):
        self.query = query
        self.materialized = materialized
        self.backing = backing  # (catalog, table) of the refresh target
        self.base_versions = base_versions


_instance_ids = itertools.count(1)
_instance_lock = threading.Lock()


class Catalog:
    def __init__(self):
        self._connectors: dict[str, Connector] = {}
        # caching-plane identity: instance_id partitions the process-global
        # plan/result caches between catalogs (tests build many runners per
        # process); generation bumps on DDL/ANALYZE so schema or stats
        # changes invalidate every cached plan against this catalog
        with _instance_lock:
            self.instance_id = next(_instance_ids)
        self.generation = 0
        # CREATE FUNCTION registry: name -> (params, return_type, body AST)
        # (reference: metadata/GlobalFunctionCatalog for SQL routines)
        self.sql_functions: dict[str, tuple] = {}
        # polymorphic table functions: name -> spi.table_function.TableFunction
        from ..spi.table_function import builtin_table_functions

        self.table_functions: dict = builtin_table_functions()
        # view registry: name -> ViewDefinition (reference:
        # metadata/MetadataManager view/materialized-view maps)
        self.views: dict = {}

    def register(self, name: str, connector: Connector) -> None:
        self._connectors[name] = connector
        self.bump_generation()

    def bump_generation(self) -> None:
        """Schema/stats changed (DDL, ANALYZE, connector registration):
        cached plans built against the old catalog state must miss."""
        self.generation += 1

    def table_versions(self, tables) -> Optional[tuple]:
        """Sorted (catalog, table, version) vector for a (catalog, table)
        iterable; None when any table is unversioned or unresolvable —
        the caching plane's shared currency."""
        from ..caching import result_cache

        return result_cache.version_vector(tuple(tables), self)

    def mv_is_stale(self, name: str) -> bool:
        """A materialized view is stale when some base table's current
        data_version differs from the vector captured at refresh.  Views
        never refreshed, or with unversioned bases, report stale (the
        conservative answer)."""
        view = self.views.get(name)
        if view is None or not view.materialized:
            raise KeyError(f"no such materialized view: {name}")
        if view.backing is None or view.base_versions is None:
            return True
        current = self.table_versions(
            [(c, t) for c, t, _v in view.base_versions])
        return current != view.base_versions

    def connector(self, name: str) -> Connector:
        if name not in self._connectors:
            raise KeyError(f"catalog not found: {name!r}")
        return self._connectors[name]

    def names(self) -> list[str]:
        return sorted(self._connectors)

    def resolve_table(self, name: str, default: str) -> tuple[str, str, TableSchema]:
        """'table', 'catalog.table' or 'catalog.schema.table' ->
        (catalog, table, schema).  Connectors with schema-qualified table
        names (connectors/system.py: 'runtime.queries') resolve the
        'schema.table' form first; the historical flat-namespace fallback
        ('catalog.x.t' -> table 't') is preserved for everything else."""
        parts = name.split(".")
        if len(parts) == 1:
            cat, table = default, parts[0]
        elif len(parts) == 2:
            cat, table = parts
            if cat not in self._connectors and default in self._connectors:
                # 'runtime.queries' under default_catalog='system': treat
                # the whole name as a schema-qualified table of the default
                try:
                    schema = self._connectors[default].get_table_schema(name)
                    return default, name, schema
                except KeyError:
                    pass
        else:
            cat = parts[0]
            qualified = ".".join(parts[1:])
            try:
                schema = self.connector(cat).get_table_schema(qualified)
                return cat, qualified, schema
            except KeyError:
                table = parts[-1]
        schema = self.connector(cat).get_table_schema(table)
        return cat, table, schema


def default_catalog(scale_factor: float = 0.01,
                    file_root: Optional[str] = None) -> Catalog:
    """Catalog with the standard engine-support connectors registered.

    ``file_root`` anchors the persistent file connector; default is a fresh
    temp directory per catalog, created lazily on first use."""
    from .file import FileConnector
    from .memory import BlackholeConnector, MemoryConnector
    from .system import SystemConnector
    from .tpch import TpchConnector

    cat = Catalog()
    cat.register("tpch", TpchConnector(scale_factor))
    cat.register("memory", MemoryConnector())
    cat.register("blackhole", BlackholeConnector())
    cat.register("file", FileConnector(file_root))
    cat.register("system", SystemConnector())
    return cat
