"""File connector: persistent columnar storage on local disk.

The engine's durable-table connector (the role plugin/trino-hive plays for
warehouse files): a table is a directory holding ``schema.json`` plus one
page file per written fragment.  Pages are the engine's serde frames
(execution/serde.py), so the same wire format serves the exchange, the
spiller, and storage.  The IO hot path — frame scanning and reads — goes
through the native C++ library (native/pagefile.cpp via ctypes,
trino_tpu/native.py) when built, with a pure-Python fallback.

Splits map 1:1 to page files, so multi-task scans parallelize over files.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
from typing import Any, Optional, Sequence


from .. import native
from ..execution.serde import deserialize_batch, serialize_batch
from ..spi.batch import ColumnBatch
from ..spi.connector import (
    ColumnSchema,
    Connector,
    ConnectorPageSink,
    ConnectorPageSource,
    Split,
    TableSchema,
    TableStatistics,
)
from ..spi.types import parse_type

__all__ = ["FileConnector"]


def _read_frames(path: str) -> list[bytes]:
    """All serde frames of a page file; native scan+read when available."""
    lib = native.load()
    if lib is not None:
        import ctypes

        cap = 4096
        while True:
            out = (ctypes.c_int64 * (2 * cap))()
            n = lib.ttp_scan_frames(path.encode(), out, cap)
            if n < 0:
                raise IOError(f"corrupt page file: {path}")
            if n <= cap:
                break
            cap = n
        frames = []
        for i in range(n):
            off, length = out[2 * i], out[2 * i + 1]
            buf = (ctypes.c_uint8 * length)()
            if lib.ttp_read_frame(path.encode(), off, length, buf) != length:
                raise IOError(f"short read: {path}")
            frames.append(bytes(buf))
        return frames
    # pure-Python fallback
    from ..execution.serde import iter_frames

    with open(path, "rb") as f:
        return list(iter_frames(f))


class _FilePageSource(ConnectorPageSource):
    def __init__(self, path: str, columns: Sequence[str]):
        self._frames = _read_frames(path)
        self._columns = list(columns)
        self._i = 0

    def get_next_batch(self) -> Optional[ColumnBatch]:
        if self._i >= len(self._frames):
            return None
        batch = deserialize_batch(self._frames[self._i])
        self._i += 1
        return batch.select(self._columns)

    def is_finished(self) -> bool:
        return self._i >= len(self._frames)


class _FilePageSink(ConnectorPageSink):
    def __init__(self, path: str):
        self._path = path
        self._file = open(path, "wb")
        self.rows = 0

    def append(self, batch: ColumnBatch) -> bool:
        from ..execution.serde import write_frame

        batch = batch.compact()
        if batch.num_rows == 0:
            return True
        write_frame(self._file, serialize_batch(batch))
        self.rows += batch.num_rows
        return True

    def finish(self) -> list[Any]:
        self._file.close()
        return [(self._path, self.rows)]


class FileConnector(Connector):
    name = "file"

    def __init__(self, root: Optional[str] = None):
        # root=None: create a temp directory lazily on first use, so idle
        # catalogs don't litter /tmp
        self._root = root
        # reentrant: metadata paths touch self.root under the lock
        self._lock = threading.RLock()
        self._sink_seq = 0

    @property
    def root(self) -> str:
        with self._lock:
            if self._root is None:
                import tempfile

                self._root = tempfile.mkdtemp(prefix="trino-tpu-file-")
            os.makedirs(self._root, exist_ok=True)
            return self._root

    # ---- metadata -------------------------------------------------------
    def _dir(self, table: str) -> str:
        return os.path.join(self.root, table)

    def _meta_path(self, table: str) -> str:
        return os.path.join(self._dir(table), "schema.json")

    def list_tables(self) -> list[str]:
        return sorted(
            d for d in os.listdir(self.root)
            if os.path.exists(self._meta_path(d)))

    def get_table_schema(self, table: str) -> TableSchema:
        try:
            with open(self._meta_path(table)) as f:
                meta = json.load(f)
        except FileNotFoundError:
            raise KeyError(f"file: no such table {table!r}")
        return TableSchema(table, tuple(
            ColumnSchema(c["name"], parse_type(c["type"]))
            for c in meta["columns"]))

    def get_table_statistics(self, table: str) -> TableStatistics:
        analyzed = getattr(self, "_analyzed_stats", {}).get(table)
        if analyzed is not None:
            return analyzed
        try:
            with open(self._meta_path(table)) as f:
                meta = json.load(f)
        except FileNotFoundError:
            return TableStatistics()
        return TableStatistics(row_count=float(meta.get("rows", 0)))

    def data_version(self, table: str):
        """On-disk content signature: the page-file list (names embed
        pid + a monotonic sink sequence, so they are never reused) plus
        the row count.  Equal signature ⇒ equal bytes on disk, across
        drop/recreate cycles and across processes."""
        try:
            with open(self._meta_path(table)) as f:
                meta = json.load(f)
        except FileNotFoundError:
            raise KeyError(f"file: no such table {table!r}")
        return f"{meta.get('rows', 0)}:{','.join(meta.get('pages', []))}"

    def _invalidate(self, table: str) -> None:
        from ..caching import result_cache

        result_cache.invalidate_table(self.name, table)

    def create_table(self, schema: TableSchema) -> None:
        d = self._dir(schema.name)
        if os.path.exists(self._meta_path(schema.name)):
            raise ValueError(f"file: table {schema.name!r} already exists")
        os.makedirs(d, exist_ok=True)
        with open(self._meta_path(schema.name), "w") as f:
            json.dump({
                "columns": [{"name": c.name, "type": str(c.type)}
                            for c in schema.columns],
                "rows": 0,
                "pages": [],
            }, f)
        self._invalidate(schema.name)

    def drop_table(self, table: str) -> None:
        shutil.rmtree(self._dir(table), ignore_errors=True)
        self._invalidate(table)

    # ---- scan -----------------------------------------------------------
    def get_splits(self, table: str, splits_per_node: int,
                   node_count: int) -> list[Split]:
        with open(self._meta_path(table)) as f:
            meta = json.load(f)
        return [Split("file", table, os.path.join(self._dir(table), p))
                for p in meta["pages"]]

    def create_page_source(self, split: Split, columns: Sequence[str],
                           constraint=None) -> ConnectorPageSource:
        return _FilePageSource(split.info, columns)

    # ---- write ----------------------------------------------------------
    def create_page_sink(self, table: str) -> ConnectorPageSink:
        self.get_table_schema(table)  # existence check
        with self._lock:
            self._sink_seq += 1
            name = f"part-{os.getpid()}-{self._sink_seq}.bin"
        return _FilePageSink(os.path.join(self._dir(table), name))

    def finish_insert(self, table: str, fragments: list[Any]) -> None:
        with self._lock:
            with open(self._meta_path(table)) as f:
                meta = json.load(f)
            for frag in fragments:
                path, rows = frag[0] if isinstance(frag, list) else frag
                if rows == 0:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    continue
                meta["pages"].append(os.path.basename(path))
                meta["rows"] += rows
            with open(self._meta_path(table), "w") as f:
                json.dump(meta, f)
        self._invalidate(table)