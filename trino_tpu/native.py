"""ctypes bindings for the native (C++) runtime pieces.

pybind11 isn't in the image, so the native library (native/pagefile.cpp —
zlib page framing, validity bitmaps, page-file scanning) binds through
ctypes.  ``load()`` builds the shared object on first use with the baked-in
toolchain and caches it next to the source; every caller must handle
``None`` (pure-Python fallback paths stay correct without the library).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

__all__ = ["load", "lib_path"]

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_ROOT, "native", "pagefile.cpp")
_SO = os.path.join(_ROOT, "native", "libpagefile.so")

_lock = threading.Lock()
_lib = None
_tried = False


def lib_path() -> str:
    return _SO


def _build() -> bool:
    for cc in ("c++", "g++"):
        try:
            proc = subprocess.run(
                [cc, "-O3", "-shared", "-fPIC", "-o", _SO, _SRC, "-lz"],
                capture_output=True, text=True, timeout=120)
            if proc.returncode == 0:
                return True
        except (OSError, subprocess.TimeoutExpired):
            continue
    return False


def load():
    """The loaded CDLL with typed signatures, or None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_SO)):
            if not os.path.exists(_SRC) or not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64 = ctypes.c_int64
        lib.ttp_deflate.argtypes = [u8p, i64, u8p, i64, ctypes.c_int]
        lib.ttp_deflate.restype = i64
        lib.ttp_deflate_bound.argtypes = [i64]
        lib.ttp_deflate_bound.restype = i64
        lib.ttp_inflate.argtypes = [u8p, i64, u8p, i64]
        lib.ttp_inflate.restype = i64
        lib.ttp_pack_bits.argtypes = [u8p, i64, u8p]
        lib.ttp_pack_bits.restype = None
        lib.ttp_unpack_bits.argtypes = [u8p, i64, u8p]
        lib.ttp_unpack_bits.restype = None
        lib.ttp_scan_frames.argtypes = [ctypes.c_char_p,
                                        ctypes.POINTER(i64), i64]
        lib.ttp_scan_frames.restype = i64
        lib.ttp_read_frame.argtypes = [ctypes.c_char_p, i64, i64, u8p]
        lib.ttp_read_frame.restype = i64
        _lib = lib
        return _lib
