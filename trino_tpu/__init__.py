"""trino_tpu — a TPU-native distributed SQL query engine.

A ground-up re-design of Trino's capabilities (reference surveyed in
SURVEY.md) for TPUs: the operator data plane compiles to XLA via jax.jit /
Pallas, repartition shuffles become ICI collectives under a device mesh, and
strings live as dictionary codes so devices only ever see fixed-width arrays.
"""

__version__ = "0.1.0"

# The engine's data plane is 64-bit (BIGINT/decimal lanes, uint64 hashes):
# x64 must be on before ANY jnp array is created.  Importing the package is
# the earliest common point — staging paths (device-side TPC-H generation,
# pin_to_device) touch jnp before trino_tpu.ops would otherwise flip this.
import jax as _jax

_jax.config.update("jax_enable_x64", True)
del _jax
