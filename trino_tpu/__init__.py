"""trino_tpu — a TPU-native distributed SQL query engine.

A ground-up re-design of Trino's capabilities (reference surveyed in
SURVEY.md) for TPUs: the operator data plane compiles to XLA via jax.jit /
Pallas, repartition shuffles become ICI collectives under a device mesh, and
strings live as dictionary codes so devices only ever see fixed-width arrays.
"""

__version__ = "0.1.0"
