"""Execution engine: kernels, operators, driver, local planner.

The data-plane replacement for core/trino-main's operator/ + execution/
packages (reference: operator/Driver.java:66, operator/Operator.java:21,
sql/planner/LocalExecutionPlanner.java:403), re-designed so that each
pipeline's hot loop is one (or a few) jitted XLA programs instead of a
bytecode-compiled per-row interpreter.
"""
