"""TupleDomain row masking at the scan boundary.

The enforcement half of predicate pushdown (planner/domains.py): scans
evaluate the advisory TupleDomain on host numpy columns BEFORE padding and
device transfer, so provably-dead rows never consume HBM bandwidth.  The
exact Filter above the scan still runs (enforced=false semantics, matching
PushPredicateIntoTableScan + the connector returning unenforced domains)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..spi.batch import ColumnBatch
from ..spi.predicate import TupleDomain, ValueSet

__all__ = ["tuple_domain_mask"]


def _valueset_mask(data: np.ndarray, vs: ValueSet) -> np.ndarray:
    if vs.is_all:
        return np.ones(len(data), dtype=bool)
    pts = vs.points()
    if pts is not None:
        if not pts:
            return np.zeros(len(data), dtype=bool)
        return np.isin(data, np.asarray(pts))
    m = np.zeros(len(data), dtype=bool)
    for r in vs.ranges:
        rm = np.ones(len(data), dtype=bool)
        if r.low is not None:
            rm &= (data >= r.low) if r.low_inclusive else (data > r.low)
        if r.high is not None:
            rm &= (data <= r.high) if r.high_inclusive else (data < r.high)
        m |= rm
    return m


def tuple_domain_mask(batch: ColumnBatch, constraint: TupleDomain,
                      name_to_idx: dict[str, int],
                      dict_cache: Optional[dict] = None) -> Optional[np.ndarray]:
    """Boolean keep-mask for a host batch under ``constraint`` (None = keep
    all rows).  Dictionary columns evaluate the domain once per dictionary
    entry and gather; ``dict_cache`` (caller-owned, keyed by (column,
    id(dictionary))) memoizes those tables — batches of one table share a
    dictionary, so the O(dict) python scan runs once per query, not per
    batch."""
    if constraint.is_none:
        return np.zeros(batch.num_rows, dtype=bool)
    mask: Optional[np.ndarray] = None
    for col, dom in constraint.domains.items():
        idx = name_to_idx.get(col)
        if idx is None:
            continue
        c = batch.columns[idx]
        data = np.asarray(c.data)
        if c.dictionary is not None:
            ck = (col, id(c.dictionary))
            tab = dict_cache.get(ck) if dict_cache is not None else None
            if tab is None:
                tab = np.array(
                    [dom.values.contains_value(
                        int(v) if isinstance(v, int) else str(v))
                     for v in c.dictionary],
                    dtype=bool)
                if dict_cache is not None:
                    dict_cache[ck] = tab
            m = tab[data] if len(tab) else np.zeros(len(data), dtype=bool)
        else:
            m = _valueset_mask(data, dom.values)
        if c.valid is not None:
            m = np.where(np.asarray(c.valid), m, dom.null_allowed)
        mask = m if mask is None else (mask & m)
    return mask
