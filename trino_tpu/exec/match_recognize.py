"""MATCH_RECOGNIZE operator: DEFINE/MEASURES evaluation over matches.

The execution half of the row-pattern stack (reference:
operator/window/pattern/LabelEvaluator.java evaluating DEFINE conditions
with running semantics, MeasureComputation for MEASURES,
PatternRecognitionPartition driving the matcher).  Pattern matching is
sequential per partition, so rows come to host as python values; the
pattern NFA lives in exec/row_pattern.py.

Expression semantics implemented (running semantics in DEFINE, final in
MEASURES, per SQL:2016 part 5):
- bare column  -> value of the CURRENT row (DEFINE) / LAST matched row
  (MEASURES)
- L.col        -> value at the LAST row labeled L so far (NULL if none)
- PREV(x[, n]) / NEXT(x[, n]) -> physical row navigation
- FIRST(L.col) / LAST(L.col)  -> first/last row labeled L
- CLASSIFIER() -> current/last row's label; MATCH_NUMBER() -> 1-based id
- sum/avg/min/max/count over (L.col | col) -> aggregate over the rows
  labeled L (or every matched row)
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..spi.batch import Column, ColumnBatch
from ..sql import ast
from .operators import BufferedInputMixin, Operator
from .row_pattern import PatternMatcher, parse_pattern

__all__ = ["MatchRecognizeOperator", "infer_measure_type"]


class _Ctx:
    """Evaluation context for one candidate row inside one match attempt."""

    def __init__(self, rows: list[dict], start: int, labels: list[str],
                 match_number: int, running: bool):
        self.rows = rows        # partition rows as dicts
        self.start = start      # partition-relative match start
        self.labels = labels    # labels assigned so far (per matched row)
        self.match_number = match_number
        self.running = running  # True in DEFINE (current row = last label)

    @property
    def cur(self) -> int:
        return self.start + len(self.labels) - 1

    def rows_with_label(self, label: Optional[str]) -> list[int]:
        out = []
        for i, l in enumerate(self.labels):
            if label is None or l == label:
                out.append(self.start + i)
        return out


def _eval(e: ast.Expr, ctx: _Ctx):
    if isinstance(e, ast.IntLiteral):
        return e.value
    if isinstance(e, ast.DoubleLiteral):
        return e.value
    if isinstance(e, ast.DecimalLiteral):
        import decimal

        return decimal.Decimal(e.text)
    if isinstance(e, ast.StringLiteral):
        return e.value
    if isinstance(e, ast.BooleanLiteral):
        return e.value
    if isinstance(e, ast.NullLiteral):
        return None
    if isinstance(e, ast.ColumnRef):
        if len(e.parts) == 2:
            # L.col: last row labeled L (running: up to the current row)
            rows = ctx.rows_with_label(e.parts[0].upper())
            if not rows:
                return None
            return ctx.rows[rows[-1]].get(e.parts[1].lower())
        return ctx.rows[ctx.cur].get(e.parts[0].lower())
    if isinstance(e, ast.BinaryOp):
        l = _eval(e.left, ctx)
        r = _eval(e.right, ctx)
        if l is None or r is None:
            return None
        op = e.op
        if op == "+":
            return l + r
        if op == "-":
            return l - r
        if op == "*":
            return l * r
        if op == "/":
            return l / r if r != 0 else None
        if op == "%":
            return l % r if r != 0 else None
        if op == "||":
            return str(l) + str(r)
        raise NotImplementedError(f"MATCH_RECOGNIZE operator {op}")
    if isinstance(e, ast.Comparison):
        l = _eval(e.left, ctx)
        r = _eval(e.right, ctx)
        if l is None or r is None:
            return None
        return {"=": l == r, "<>": l != r, "!=": l != r, "<": l < r,
                "<=": l <= r, ">": l > r, ">=": l >= r}[e.op]
    if isinstance(e, ast.LogicalOp):
        vals = [_eval(t, ctx) for t in e.terms]
        if e.op == "AND":
            if any(v is False for v in vals):
                return False
            return None if any(v is None for v in vals) else True
        if any(v is True for v in vals):
            return True
        return None if any(v is None for v in vals) else False
    if isinstance(e, ast.Not):
        v = _eval(e.operand, ctx)
        return None if v is None else (not v)
    if isinstance(e, ast.IsNull):
        r = _eval(e.operand, ctx) is None
        return (not r) if e.negated else r
    if isinstance(e, ast.FunctionCall):
        return _eval_call(e, ctx)
    if isinstance(e, ast.Between):
        v = _eval(e.operand, ctx)
        lo = _eval(e.low, ctx)
        hi = _eval(e.high, ctx)
        if v is None or lo is None or hi is None:
            return None
        r = lo <= v <= hi
        return (not r) if e.negated else r
    raise NotImplementedError(
        f"MATCH_RECOGNIZE expression: {type(e).__name__}")


def _nav_target(e: ast.Expr, ctx: _Ctx, which: str):
    """FIRST/LAST(L.col) positional navigation."""
    if isinstance(e, ast.ColumnRef) and len(e.parts) == 2:
        rows = ctx.rows_with_label(e.parts[0].upper())
        col = e.parts[1].lower()
    elif isinstance(e, ast.ColumnRef):
        rows = ctx.rows_with_label(None)
        col = e.parts[0].lower()
    else:
        raise NotImplementedError(f"{which}() needs a column reference")
    if not rows:
        return None
    return ctx.rows[rows[0] if which == "first" else rows[-1]].get(col)


def _eval_call(e: ast.FunctionCall, ctx: _Ctx):
    name = e.name.lower()
    if name == "classifier":
        return ctx.labels[-1] if ctx.labels else None
    if name == "match_number":
        return ctx.match_number
    if name in ("prev", "next"):
        off = 1
        if len(e.args) > 1:
            off = int(_eval(e.args[1], ctx))
        arg = e.args[0]
        if not isinstance(arg, ast.ColumnRef):
            raise NotImplementedError(f"{name}() needs a column reference")
        col = arg.parts[-1].lower()
        if len(arg.parts) == 2:
            # PREV(A.x): navigate from the LAST row labeled A (SQL:2016)
            anchor = ctx.rows_with_label(arg.parts[0].upper())
            if not anchor:
                return None
            base = anchor[-1]
        else:
            base = ctx.cur
        idx = base + (-off if name == "prev" else off)
        if idx < 0 or idx >= len(ctx.rows):
            return None
        return ctx.rows[idx].get(col)
    if name in ("first", "last"):
        return _nav_target(e.args[0], ctx, name)
    if name in ("sum", "avg", "min", "max", "count"):
        if name == "count" and (e.is_star or not e.args):
            return len(ctx.rows_with_label(None))
        arg = e.args[0]
        if isinstance(arg, ast.ColumnRef) and len(arg.parts) == 2:
            rows = ctx.rows_with_label(arg.parts[0].upper())
            col = arg.parts[1].lower()
        elif isinstance(arg, ast.ColumnRef):
            rows = ctx.rows_with_label(None)
            col = arg.parts[0].lower()
        else:
            raise NotImplementedError(
                "MATCH_RECOGNIZE aggregates need a column reference")
        vals = [ctx.rows[i].get(col) for i in rows]
        vals = [v for v in vals if v is not None]
        if name == "count":
            return len(vals)
        if not vals:
            return None
        if name == "sum":
            return sum(vals)
        if name == "avg":
            return sum(vals) / len(vals)
        return min(vals) if name == "min" else max(vals)
    raise NotImplementedError(f"MATCH_RECOGNIZE function: {name}")


def infer_measure_type(e: ast.Expr, schema: dict):
    """Static type of a measure expression given {column -> Type}."""
    from ..spi.types import (
        BIGINT,
        BOOLEAN,
        DOUBLE,
        VARCHAR,
        common_super_type,
    )

    if isinstance(e, ast.IntLiteral):
        return BIGINT
    if isinstance(e, (ast.DoubleLiteral, ast.DecimalLiteral)):
        return DOUBLE
    if isinstance(e, ast.StringLiteral):
        return VARCHAR
    if isinstance(e, ast.BooleanLiteral):
        return BOOLEAN
    if isinstance(e, ast.ColumnRef):
        return schema.get(e.parts[-1].lower(), DOUBLE)
    if isinstance(e, ast.FunctionCall):
        n = e.name.lower()
        if n == "classifier":
            return VARCHAR
        if n in ("match_number", "count"):
            return BIGINT
        if n == "avg":
            return DOUBLE
        if n in ("sum", "min", "max", "first", "last", "prev", "next"):
            return infer_measure_type(e.args[0], schema) if e.args else DOUBLE
        return DOUBLE
    if isinstance(e, ast.BinaryOp):
        a = infer_measure_type(e.left, schema)
        b = infer_measure_type(e.right, schema)
        return common_super_type(a, b) or DOUBLE
    if isinstance(e, (ast.Comparison, ast.LogicalOp, ast.Not, ast.IsNull)):
        return BOOLEAN
    return DOUBLE


class MatchRecognizeOperator(BufferedInputMixin, Operator):
    """ONE ROW PER MATCH pattern recognition (reference:
    sql/planner/plan/PatternRecognitionNode.java:47 executed through
    WindowOperator's pattern partitioner)."""

    def __init__(self, partition_channels, order_keys, pattern_text: str,
                 defines, measures, skip_past: bool,
                 output_names, output_types, input_names):
        self.partition_channels = list(partition_channels)
        self.order_keys = list(order_keys)  # [(channel, ascending)]
        self.pattern = parse_pattern(pattern_text)
        self.defines = {k.upper(): v for k, v in defines}
        self.measures = list(measures)  # [(expr, name)]
        self.skip_past = skip_past
        self.output_names = list(output_names)
        self.output_types = list(output_types)
        self.input_names = [n.lower() for n in input_names]
        self._batches: list[ColumnBatch] = []
        self._result: Optional[ColumnBatch] = None
        self._emitted = False

    def add_input(self, batch: ColumnBatch) -> None:
        if batch.num_rows:
            self._batches.append(batch)
            self.account_memory()

    def finish_input(self) -> None:
        super().finish_input()
        self._result = self._compute()
        self.release_memory()

    def _compute(self) -> Optional[ColumnBatch]:
        if not self._batches:
            return None
        inp = ColumnBatch.concat(self._batches)
        rows = [dict(zip(self.input_names, r)) for r in inp.to_pylist()]

        # partition + order on host (python values; partitions are small
        # relative to the scan — the heavy filtering already ran on device)
        def pkey(i):
            return tuple(
                (rows[i][self.input_names[c]] is None,
                 rows[i][self.input_names[c]])
                for c in self.partition_channels)

        def okey(i):
            out = []
            for c, asc in self.order_keys:
                v = rows[i][self.input_names[c]]
                # ASC defaults NULLS LAST, DESC defaults NULLS FIRST
                out.append((v is None if asc else v is not None,
                            v if asc else _Desc(v)))
            return tuple(out)

        idx = sorted(range(len(rows)), key=lambda i: (pkey(i), okey(i)))
        out_rows: list[tuple] = []
        start = 0
        while start < len(idx):
            end = start
            while end < len(idx) and pkey(idx[end]) == pkey(idx[start]):
                end += 1
            part_rows = [rows[i] for i in idx[start:end]]
            out_rows.extend(self._match_partition(part_rows))
            start = end
        cols = []
        for j, t in enumerate(self.output_types):
            cols.append(Column.from_values(
                t, [r[j] for r in out_rows]))
        return ColumnBatch(self.output_names, cols)

    def _match_partition(self, part_rows: list[dict]) -> list[tuple]:
        holder: dict = {}

        def predicate(label: str, pos: int, labels: list[str]) -> bool:
            cond = self.defines.get(label)
            if cond is None:
                return True  # undefined label matches any row (spec)
            ctx = _Ctx(part_rows, pos - len(labels) + 1, labels,
                       holder["m"].next_match_number, True)
            return _eval(cond, ctx) is True

        matcher = PatternMatcher(self.pattern, predicate)
        holder["m"] = matcher
        out = []
        for m in matcher.find_matches(len(part_rows), self.skip_past):
            ctx = _Ctx(part_rows, m.start, m.labels, m.match_number, False)
            row = []
            for c in self.partition_channels:
                row.append(part_rows[m.start][self.input_names[c]])
            for expr, _name in self.measures:
                row.append(_eval(expr, ctx))
            out.append(tuple(row))
        return out

    def get_output(self) -> Optional[ColumnBatch]:
        if self._result is not None and not self._emitted:
            self._emitted = True
            return self._result
        return None

    def is_finished(self) -> bool:
        return self.input_done and (self._emitted or self._result is None)


class _Desc:
    """Order-inverting sort key: works for ANY comparable python value
    (negating strings char-by-char breaks on unequal lengths)."""

    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other) -> bool:
        return other.v < self.v

    def __eq__(self, other) -> bool:
        return self.v == other.v
