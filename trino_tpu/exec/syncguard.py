"""SyncGuard: host-transfer accounting for the operator hot loops.

Per-batch device->host scalar syncs dominated the r4 join profile (each
blocking RPC over a tunneled device costs ~120 ms), so the sync-free rework
needs an instrument that (a) COUNTS every host transfer the exec layer
performs, attributed to a tag, (b) distinguishes transfers that actually
blocked from polls of an async copy that had already landed, and (c) in
tests, FORBIDS any transfer inside a declared hot-loop region so the
zero-sync contract is asserted rather than assumed.

Usage in exec code — every deliberate host sync goes through this module
instead of raw ``int(np.asarray(...))`` / ``jax.device_get`` (the grep lint
in tools/lint_host_sync.py flags raw patterns):

    from . import syncguard as SG
    n = SG.fetch(jnp.sum(live), "join.cross-live")        # blocking, counted

    h = SG.async_scalar(total, "join.pair-total")          # starts D2H copy
    ...dispatch more device work...
    v = h.get()          # counted as a poll hit if the copy already landed

The counters roll up into :class:`SyncStats` (merged into QueryStats like
ScanIngestStats, rendered by EXPLAIN ANALYZE, exported as ``trino.exec.*``
span attributes).  ``hot_region`` marks a steady-state operator hot loop;
``forbidden`` mode (tests) raises :class:`SyncViolation` on any blocking
transfer inside a hot region.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass, field, fields

__all__ = [
    "SyncStats",
    "SyncViolation",
    "AsyncScalar",
    "fetch",
    "async_scalar",
    "count_sync",
    "hot_region",
    "forbidden",
    "snapshot",
    "take_delta",
    "stats",
]


class SyncViolation(AssertionError):
    """A blocking host sync happened inside a declared hot-loop region while
    SyncGuard was in ``forbidden`` mode (test enforcement)."""


@dataclass
class SyncStats:
    """Host-transfer counters for the exec layer (one global accumulator;
    ``take_delta`` snapshots per query).  ``host_syncs`` counts every
    device->host value materialization the exec layer asked for;
    ``blocking_syncs`` the subset that had to wait on the device;
    ``async_polls``/``poll_hits`` the async-copy handles created and how many
    had already landed when read (a hit costs ~0 instead of a device RTT).
    ``expand_overflows``/``expand_retries`` count padded-expand buckets that
    proved too small and the re-runs that fixed them."""

    host_syncs: int = 0
    blocking_syncs: int = 0
    async_polls: int = 0
    poll_hits: int = 0
    expand_overflows: int = 0
    expand_retries: int = 0
    hot_loop_syncs: int = 0      # blocking syncs inside hot regions (want: 0)
    by_tag: dict = field(default_factory=dict)

    def merge(self, other: "SyncStats") -> None:
        for f in fields(self):
            if f.name == "by_tag":
                for k, v in other.by_tag.items():
                    self.by_tag[k] = self.by_tag.get(k, 0) + v
            else:
                setattr(self, f.name, getattr(self, f.name)
                        + getattr(other, f.name))

    def text(self) -> str:
        tags = " ".join(f"{k}={v}" for k, v in sorted(self.by_tag.items()))
        return (
            f"exec: {self.host_syncs} host syncs "
            f"({self.blocking_syncs} blocking, {self.hot_loop_syncs} in hot "
            f"loops), {self.poll_hits}/{self.async_polls} async polls ready, "
            f"expand overflow {self.expand_overflows}/"
            f"retry {self.expand_retries}"
            + (f" [{tags}]" if tags else "")
        )


class _State(threading.local):
    hot_depth = 0


_STATE = _State()
_LOCK = threading.Lock()
_STATS = SyncStats()
_FORBID = False  # set only by tests via forbidden()


def stats() -> SyncStats:
    """The live global accumulator (shared across threads)."""
    return _STATS


def snapshot() -> SyncStats:
    """Copy of the current totals."""
    with _LOCK:
        s = SyncStats(**{f.name: getattr(_STATS, f.name)
                         for f in fields(_STATS) if f.name != "by_tag"})
        s.by_tag = dict(_STATS.by_tag)
        return s


def take_delta(since: SyncStats) -> SyncStats:
    """Counters accumulated after ``since`` (per-query attribution)."""
    now = snapshot()
    d = SyncStats()
    for f in fields(d):
        if f.name == "by_tag":
            for k, v in now.by_tag.items():
                dv = v - since.by_tag.get(k, 0)
                if dv:
                    d.by_tag[k] = dv
        else:
            setattr(d, f.name, getattr(now, f.name) - getattr(since, f.name))
    return d


def _is_ready(x) -> bool:
    if isinstance(x, (tuple, list)):
        return all(_is_ready(e) for e in x)
    try:
        return bool(x.is_ready())
    except AttributeError:
        return True  # numpy / python scalar: already host-resident


def count_sync(tag: str, blocking: bool = True) -> None:
    """Record a host sync performed elsewhere (e.g. batched result fetch)."""
    in_hot = _STATE.hot_depth > 0
    if blocking and in_hot and _FORBID:
        raise SyncViolation(
            f"blocking host sync '{tag}' inside a SyncGuard hot region")
    with _LOCK:
        _STATS.host_syncs += 1
        if blocking:
            _STATS.blocking_syncs += 1
            if in_hot:
                _STATS.hot_loop_syncs += 1
        _STATS.by_tag[tag] = _STATS.by_tag.get(tag, 0) + 1


def count_overflow(retried: bool = True) -> None:
    with _LOCK:
        _STATS.expand_overflows += 1
        if retried:
            _STATS.expand_retries += 1


def fetch(x, tag: str):
    """Blocking device->host materialization, counted (and forbidden inside
    hot regions under test enforcement).  Returns a numpy value."""
    import jax

    count_sync(tag, blocking=not _is_ready(x))
    return jax.device_get(x)


class AsyncScalar:
    """Handle for a device scalar whose D2H copy was started asynchronously.
    ``get()`` blocks only if the copy has not landed yet (counted as a poll
    hit when it has); ``ready()``/``get_if_ready()`` never block."""

    __slots__ = ("value", "tag")

    def __init__(self, value, tag: str):
        self.value = value
        self.tag = tag
        try:
            value.copy_to_host_async()
        except AttributeError:
            pass

    def ready(self) -> bool:
        return _is_ready(self.value)

    def get(self):
        import jax

        hit = self.ready()
        with _LOCK:
            _STATS.async_polls += 1
            if hit:
                _STATS.poll_hits += 1
        if not hit:
            # the copy is in flight but we must wait: a genuine blocking sync
            count_sync(self.tag, blocking=True)
        return jax.device_get(self.value)

    def get_if_ready(self):
        """Non-blocking: the value if the copy landed, else None."""
        if not self.ready():
            with _LOCK:
                _STATS.async_polls += 1
            return None
        import jax

        with _LOCK:
            _STATS.async_polls += 1
            _STATS.poll_hits += 1
        return jax.device_get(self.value)


def async_scalar(x, tag: str) -> AsyncScalar:
    return AsyncScalar(x, tag)


@contextmanager
def hot_region():
    """Marks an operator steady-state hot loop: blocking syncs inside are
    tallied separately (and raise under ``forbidden``)."""
    _STATE.hot_depth += 1
    try:
        yield
    finally:
        _STATE.hot_depth -= 1


@contextmanager
def forbidden():
    """Test enforcement: any blocking sync inside a hot region raises
    SyncViolation.  Not thread-safe by design — tests only."""
    global _FORBID
    prev = _FORBID
    _FORBID = True
    try:
        yield
    finally:
        _FORBID = prev
