"""MATCH_RECOGNIZE row-pattern engine.

Mirrors the reference's row-pattern stack (sql/planner/rowpattern/ pattern
IR + operator/window/pattern/ matcher — LabelEvaluator.java,
MatchAggregation.java; plan node PatternRecognitionNode.java:47) in a
host-side engine: patterns compile to a Thompson NFA over label predicates
and matching runs per partition with greedy quantifier semantics
(backtracking, longest-match-first like the reference's matcher).

Scope (the widely-used core): concatenation, alternation ``|``, grouping,
quantifiers ``* + ? {n,m}``, ONE ROW PER MATCH, AFTER MATCH SKIP PAST LAST
ROW / TO NEXT ROW, CLASSIFIER()/MATCH_NUMBER(), FIRST/LAST/PREV/NEXT in
DEFINE/MEASURES, and aggregates over matched rows.  Pattern evaluation is
inherently sequential per partition, so it lives on host — partitions
themselves parallelize across tasks like any partitioned operator."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from ..spi.errors import GENERIC_INTERNAL_ERROR, TrinoError

__all__ = ["parse_pattern", "PatternMatcher", "Match",
           "PatternSyntaxError"]


class PatternSyntaxError(ValueError):
    """Malformed MATCH_RECOGNIZE pattern text — the query's own bug.
    Registered in spi.errors._USER_ERROR_CLASS_NAMES so classify() maps it
    to GENERIC_USER_ERROR (never retried), like AnalysisError/ParseError."""


# --------------------------------------------------------------------------
# pattern AST + parser:  A (B|C)+ D?  {n,m} quantifiers


@dataclass(frozen=True)
class PLabel:
    name: str


@dataclass(frozen=True)
class PSeq:
    parts: tuple


@dataclass(frozen=True)
class PAlt:
    options: tuple


@dataclass(frozen=True)
class PQuant:
    inner: object
    low: int
    high: Optional[int]  # None = unbounded
    greedy: bool = True


class _PatternParser:
    def __init__(self, tokens: list[str]):
        self.toks = tokens
        self.i = 0

    @property
    def cur(self) -> Optional[str]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def parse(self):
        e = self._alt()
        if self.cur is not None:
            raise PatternSyntaxError(
                f"unexpected pattern token {self.cur!r}")
        return e

    def _alt(self):
        opts = [self._seq()]
        while self.cur == "|":
            self.i += 1
            opts.append(self._seq())
        return opts[0] if len(opts) == 1 else PAlt(tuple(opts))

    def _seq(self):
        parts = []
        while self.cur is not None and self.cur not in ("|", ")"):
            parts.append(self._quant())
        if not parts:
            raise PatternSyntaxError("empty pattern")
        return parts[0] if len(parts) == 1 else PSeq(tuple(parts))

    def _quant(self):
        atom = self._atom()
        c = self.cur
        if c == "*":
            self.i += 1
            return PQuant(atom, 0, None)
        if c == "+":
            self.i += 1
            return PQuant(atom, 1, None)
        if c == "?":
            self.i += 1
            return PQuant(atom, 0, 1)
        if c == "{":
            self.i += 1
            lo = ""
            while self.cur and self.cur.isdigit():
                lo += self.cur
                self.i += 1
            hi: Optional[str] = lo
            if self.cur == ",":
                self.i += 1
                hi = ""
                while self.cur and self.cur.isdigit():
                    hi += self.cur
                    self.i += 1
            if self.cur != "}":
                raise PatternSyntaxError("unterminated {n,m} quantifier")
            self.i += 1
            return PQuant(atom, int(lo or 0),
                          int(hi) if hi else None)
        return atom

    def _atom(self):
        c = self.cur
        if c == "(":
            self.i += 1
            e = self._alt()
            if self.cur != ")":
                raise PatternSyntaxError("unbalanced ( in pattern")
            self.i += 1
            return e
        if c is None or not (c[0].isalpha() or c[0] == "_"):
            raise PatternSyntaxError(f"expected pattern label, got {c!r}")
        self.i += 1
        return PLabel(c.upper())


def _tokenize_pattern(text: str) -> list[str]:
    toks: list[str] = []
    i = 0
    while i < len(text):
        ch = text[i]
        if ch.isspace():
            i += 1
            continue
        if ch.isalpha() or ch == "_":
            j = i
            while j < len(text) and (text[j].isalnum() or text[j] == "_"):
                j += 1
            toks.append(text[i:j])
            i = j
            continue
        if ch.isdigit():
            j = i
            while j < len(text) and text[j].isdigit():
                j += 1
            toks.append(text[i:j])
            i = j
            continue
        toks.append(ch)
        i += 1
    return toks


def parse_pattern(text: str):
    return _PatternParser(_tokenize_pattern(text)).parse()


def pattern_labels(p) -> list[str]:
    if isinstance(p, PLabel):
        return [p.name]
    if isinstance(p, PSeq):
        out = []
        for x in p.parts:
            for l in pattern_labels(x):
                if l not in out:
                    out.append(l)
        return out
    if isinstance(p, PAlt):
        out = []
        for x in p.options:
            for l in pattern_labels(x):
                if l not in out:
                    out.append(l)
        return out
    return pattern_labels(p.inner)


# --------------------------------------------------------------------------
# matcher: greedy backtracking over label predicates


@dataclass
class Match:
    start: int  # partition-relative row index
    end: int    # exclusive
    labels: list[str]  # per matched row, the classifier label
    match_number: int = 0


class PatternMatcher:
    """``predicate(label, row_idx, labels_so_far) -> bool`` decides whether
    the DEFINE condition for ``label`` holds on the row given the current
    prefix assignment (supports PREV/FIRST/LAST semantics in the caller).
    Greedy quantifiers with backtracking — the reference matcher's
    preferment order (Matcher.java over the pattern's preferred branches)."""

    def __init__(self, pattern, predicate: Callable[[str, int, list], bool],
                 max_rows_per_match: int = 10_000):
        self.pattern = pattern
        self.predicate = predicate
        self.max_rows = max_rows_per_match
        self.next_match_number = 1

    def _try(self, p, pos: int, n: int, labels: list) -> Optional[int]:
        """Longest (greedy, preferment-ordered) match of ``p`` at pos."""
        return self._match(p, pos, n, labels, lambda end: end)

    def _match(self, p, pos: int, n: int, labels: list,
               cont) -> Optional[int]:
        """Full-backtracking CPS matcher: ``cont(pos')`` tries the REST of
        the pattern; a failing continuation re-enters earlier alternatives
        and shorter quantifier expansions (the reference matcher's
        preferment order over every branch point)."""
        if isinstance(p, PLabel):
            if pos >= n or len(labels) >= self.max_rows:
                return None
            labels.append(p.name)
            if self.predicate(p.name, pos, labels):
                r = cont(pos + 1)
                if r is not None:
                    return r
            labels.pop()
            return None
        if isinstance(p, PSeq):
            def seq_cont(k):
                if k == len(p.parts):
                    return cont
                return lambda pos2: self._match(
                    p.parts[k], pos2, n, labels, seq_cont(k + 1))

            return seq_cont(0)(pos)
        if isinstance(p, PAlt):
            for opt in p.options:
                mark = len(labels)
                r = self._match(opt, pos, n, labels, cont)
                if r is not None:
                    return r
                del labels[mark:]
            return None
        if isinstance(p, PQuant):
            q = p

            def rep(pos2: int, count: int) -> Optional[int]:
                if q.high is None or count < q.high:
                    mark = len(labels)

                    def more(pos3: int) -> Optional[int]:
                        if pos3 == pos2:
                            # zero-width repetition: stop expanding
                            return cont(pos3) if count + 1 >= q.low else None
                        return rep(pos3, count + 1)

                    r = self._match(q.inner, pos2, n, labels, more)
                    if r is not None:
                        return r
                    del labels[mark:]
                if count >= q.low:
                    return cont(pos2)
                return None

            return rep(pos, 0)
        raise TrinoError(GENERIC_INTERNAL_ERROR,
                         f"unhandled pattern node {type(p).__name__}")

    def find_matches(self, n: int, skip_past_last: bool = True) -> list[Match]:
        """Scan a partition of ``n`` rows, emitting non-overlapping matches
        (AFTER MATCH SKIP PAST LAST ROW) or all matches advancing one row
        (SKIP TO NEXT ROW).  ``next_match_number`` is live during the scan
        so DEFINE predicates can evaluate MATCH_NUMBER()."""
        out: list[Match] = []
        pos = 0
        mn = 0
        while pos < n:
            self.next_match_number = mn + 1
            labels: list[str] = []
            end = self._try(self.pattern, pos, n, labels)
            if end is not None and end > pos:
                mn += 1
                out.append(Match(pos, end, list(labels), mn))
                pos = end if skip_past_last else pos + 1
            else:
                pos += 1
        return out
