"""Jitted window-function kernels.

The TPU-native replacement for the reference's WindowOperator + per-function
window frame machinery (reference: operator/WindowOperator.java:69,
operator/window/FramedWindowFunction.java, operator/PagesIndex.java).  Where
the JVM design walks rows of a sorted PagesIndex per partition, this lowers
the WHOLE window computation — lexsort, partition/peer boundary detection,
every window function, scatter back to input order — into ONE jitted XLA
program per (window spec, shape bucket):

- partition / peer boundaries come from vectorized neighbor compares on the
  sorted keys (NaN-aware, validity-aware — same semantics as the grouping
  kernel in exec/kernels.py);
- ranking functions are index arithmetic over boundary prefix scans
  (``lax.cummax`` / ``cumsum``);
- framed aggregates are prefix-sum differences (sum/count/avg) or segmented
  scans (min/max) — O(n) work, no per-partition loop;
- navigation functions (lag/lead/first/last/nth_value) are clamped gathers.

Everything is fixed-shape; the only host interaction is the registry-memo
(caching/executable_cache.py) compile lookup.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..caching.executable_cache import jit_memo

import jax
import jax.numpy as jnp
import numpy as np

from .kernels import _canon_float, _neq

__all__ = ["compute_windows", "WINDOW_RANK_FNS", "WINDOW_VALUE_FNS",
           "WINDOW_AGG_FNS"]

WINDOW_RANK_FNS = {"row_number", "rank", "dense_rank", "percent_rank",
                   "cume_dist", "ntile"}
WINDOW_VALUE_FNS = {"lag", "lead", "first_value", "last_value", "nth_value"}
WINDOW_AGG_FNS = {"count", "count_star", "sum", "avg", "min", "max"}


def _sort_transform(d, ascending: bool, valid, nulls_first: bool):
    """Produce lexsort columns for one key, replicating kernels.sort_perm's
    rules (desc flip, NaN rank, NULL rank) inside a traced context.  Returns
    minor-to-major list fragments (value first, then rank columns)."""
    cols = []
    kind = np.dtype(d.dtype).kind
    if not ascending:
        if kind == "b":
            d = ~d
        elif kind == "f":
            d = -d.astype(jnp.float64)
        else:
            d = ~d.astype(jnp.int64)
    if kind == "f":
        nan = jnp.isnan(d)
        nan_rank = jnp.where(nan, 1 if ascending else 0, 0 if ascending else 1)
        d = jnp.where(nan, jnp.zeros((), d.dtype), d)
        cols.append(d)
        cols.append(nan_rank)
    else:
        cols.append(d)
    if valid is not None:
        null_rank = (jnp.where(valid, 1, 0) if nulls_first
                     else jnp.where(valid, 0, 1))
        cols.append(null_rank)
    return cols


def _boundary(datas, valids, n):
    """True where sorted row i starts a new run of the given key columns."""
    new = None
    for d, v in zip(datas, valids):
        if np.dtype(d.dtype).kind == "f":
            d = _canon_float(d)
        if v is not None:
            d = jnp.where(v, d, jnp.zeros((), d.dtype))
        diff = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                                _neq(d[1:], d[:-1])])
        if v is not None:
            diff = diff | jnp.concatenate(
                [jnp.ones((1,), jnp.bool_), v[1:] != v[:-1]])
        new = diff if new is None else (new | diff)
    if new is None:
        return jnp.zeros((n,), jnp.bool_).at[0].set(True)
    return new


def _seg_scan(op, x, starts):
    """Segmented inclusive scan: ``op`` accumulates within runs delimited by
    ``starts`` (True = first row of a segment)."""

    def combine(a, b):
        va, fa = a
        vb, fb = b
        return jnp.where(fb, vb, op(va, vb)), fa | fb

    v, _ = jax.lax.associative_scan(combine, (x, starts))
    return v


def _suffix_min_index(mask):
    """For each i: the smallest j >= i with mask[j] (n if none)."""
    n = mask.shape[0]
    idx = jnp.where(mask, jnp.arange(n), n)
    return jnp.flip(jax.lax.cummin(jnp.flip(idx)))


def _prefix_upto(x, part_start_idx):
    """Partition-relative inclusive prefix sum evaluated at arbitrary sorted
    index j: returns fn(j) usable with out-of-segment clamping."""
    cs = jnp.cumsum(x)
    zero = jnp.zeros((1,), cs.dtype)
    cs0 = jnp.concatenate([zero, cs])  # cs0[j+1] = sum x[0..j]

    def upto(j, start):
        """sum of x[start..j]; j < start -> 0 (empty)."""
        j = jnp.maximum(j, start - 1)
        return cs0[j + 1] - cs0[start]

    return upto


@jit_memo("window._window_program")
def _window_program(
    n_part: int,
    part_valid: tuple[bool, ...],
    order_spec: tuple[tuple[bool, bool, bool], ...],  # (has_valid, asc, nf)
    fn_spec: tuple,  # (fn, n_args, arg_valid tuple, offset, frame, dtype_str)
):
    @jax.jit
    def program(shape_carrier, *flat):
        i = 0
        part, pvalid = [], []
        for k in range(n_part):
            part.append(flat[i]); i += 1
            if part_valid[k]:
                pvalid.append(flat[i]); i += 1
            else:
                pvalid.append(None)
        order, ovalid = [], []
        for (hv, _asc, _nf) in order_spec:
            order.append(flat[i]); i += 1
            if hv:
                ovalid.append(flat[i]); i += 1
            else:
                ovalid.append(None)
        fn_args = []
        for (_fn, n_args, arg_valid, _off, _frame, _dt) in fn_spec:
            args = []
            for a in range(n_args):
                d = flat[i]; i += 1
                v = None
                if arg_valid[a]:
                    v = flat[i]; i += 1
                args.append((d, v))
            fn_args.append(args)

        n = shape_carrier.shape[0]
        arange = jnp.arange(n)

        # ---- sort: partition keys (major) then order keys ----------------
        lex = []  # built minor-to-major then reversed
        for (hv, asc, nf), d, v in zip(reversed(order_spec),
                                       list(reversed(order)),
                                       list(reversed(ovalid))):
            frag = _sort_transform(d, asc, v, nf)
            lex.extend(frag)
        for d, v in zip(reversed(part), reversed(pvalid)):
            frag = _sort_transform(d, True, v, False)
            lex.extend(frag)
        if lex:
            perm = jnp.lexsort(tuple(lex))
        else:
            perm = arange

        part_s = [d[perm] for d in part]
        pval_s = [None if v is None else v[perm] for v in pvalid]
        ord_s = [d[perm] for d in order]
        oval_s = [None if v is None else v[perm] for v in ovalid]

        # ---- boundaries ---------------------------------------------------
        part_start = _boundary(part_s, pval_s, n)
        if order:
            peer_start = part_start | _boundary(ord_s, oval_s, n)
        else:
            peer_start = part_start
        part_start_idx = jax.lax.cummax(jnp.where(part_start, arange, 0))
        peer_start_idx = jax.lax.cummax(jnp.where(peer_start, arange, 0))
        part_last = jnp.concatenate([part_start[1:], jnp.ones((1,), jnp.bool_)])
        peer_last = jnp.concatenate([peer_start[1:], jnp.ones((1,), jnp.bool_)])
        part_end_idx = _suffix_min_index(part_last)
        peer_end_idx = _suffix_min_index(peer_last)
        part_rows = part_end_idx - part_start_idx + 1

        outs = []
        for (fn, _n_args, _argv, offset, frame, dtype_str), args in zip(
                fn_spec, fn_args):
            dtype = jnp.dtype(dtype_str)
            x, xv = (args[0] if args else (None, None))
            xs = None if x is None else x[perm]
            xvs = (jnp.ones((n,), jnp.bool_) if (x is None or xv is None)
                   else xv[perm])

            if fn == "row_number":
                res = (arange - part_start_idx + 1).astype(dtype)
                val = jnp.ones((n,), jnp.bool_)
            elif fn == "rank":
                res = (peer_start_idx - part_start_idx + 1).astype(dtype)
                val = jnp.ones((n,), jnp.bool_)
            elif fn == "dense_rank":
                cs = jnp.cumsum(peer_start.astype(jnp.int64))
                res = (cs - cs[part_start_idx] + 1).astype(dtype)
                val = jnp.ones((n,), jnp.bool_)
            elif fn == "percent_rank":
                rank = peer_start_idx - part_start_idx + 1
                denom = jnp.maximum(part_rows - 1, 1)
                res = jnp.where(part_rows == 1, 0.0,
                                (rank - 1).astype(jnp.float64)
                                / denom.astype(jnp.float64))
                val = jnp.ones((n,), jnp.bool_)
            elif fn == "cume_dist":
                res = ((peer_end_idx - part_start_idx + 1).astype(jnp.float64)
                       / part_rows.astype(jnp.float64))
                val = jnp.ones((n,), jnp.bool_)
            elif fn == "ntile":
                tiles = offset
                rn0 = arange - part_start_idx  # 0-based row number
                size = part_rows // tiles
                rem = part_rows % tiles
                big = rem * (size + 1)
                in_big = rn0 < big
                safe_size = jnp.maximum(size, 1)
                res = jnp.where(
                    in_big,
                    rn0 // jnp.maximum(size + 1, 1),
                    rem + (rn0 - big) // safe_size,
                ) + 1
                # more partitions than rows: every row its own tile
                res = jnp.where(size == 0, rn0 + 1, res).astype(dtype)
                val = jnp.ones((n,), jnp.bool_)
            elif fn in ("lag", "lead"):
                j = arange - offset if fn == "lag" else arange + offset
                in_part = ((j >= part_start_idx) & (j <= part_end_idx)
                           if fn == "lag"
                           else (j <= part_end_idx) & (j >= part_start_idx))
                jc = jnp.clip(j, 0, n - 1)
                got = xs[jc]
                gotv = xvs[jc]
                if len(args) > 1:  # explicit default (evaluated at current row)
                    dd, dv = args[1]
                    dds = dd[perm]
                    ddv = (jnp.ones((n,), jnp.bool_) if dv is None
                           else dv[perm])
                    res = jnp.where(in_part, got, dds.astype(got.dtype))
                    val = jnp.where(in_part, gotv, ddv)
                else:
                    res = jnp.where(in_part, got, jnp.zeros((), got.dtype))
                    val = in_part & gotv
                res = res.astype(dtype)
            elif fn in ("first_value", "last_value", "nth_value"):
                fs, fe = _frame_indices(
                    frame, arange, part_start_idx, part_end_idx,
                    peer_start_idx, peer_end_idx)
                fs = jnp.maximum(fs, part_start_idx)
                fe = jnp.minimum(fe, part_end_idx)
                nonempty = fs <= fe
                if fn == "first_value":
                    j = fs
                elif fn == "last_value":
                    j = fe
                else:
                    j = fs + (offset - 1)
                    nonempty = nonempty & (j <= fe)
                jc = jnp.clip(j, 0, n - 1)
                res = jnp.where(nonempty, xs[jc], jnp.zeros((), xs.dtype))
                val = nonempty & xvs[jc]
                res = res.astype(dtype)
            else:  # framed aggregate
                fs, fe = _frame_indices(
                    frame, arange, part_start_idx, part_end_idx,
                    peer_start_idx, peer_end_idx)
                fs = jnp.maximum(fs, part_start_idx)
                fe = jnp.minimum(fe, part_end_idx)
                if fn == "count_star":
                    res = jnp.maximum(fe - fs + 1, 0).astype(dtype)
                    val = jnp.ones((n,), jnp.bool_)
                elif fn in ("count", "sum", "avg"):
                    cnt_upto = _prefix_upto(xvs.astype(jnp.int64),
                                            part_start_idx)
                    cnt = cnt_upto(fe, part_start_idx) - cnt_upto(
                        fs - 1, part_start_idx)
                    cnt = jnp.maximum(cnt, 0)  # empty frame
                    if fn == "count":
                        res = cnt.astype(dtype)
                        val = jnp.ones((n,), jnp.bool_)
                    else:
                        acc = jnp.where(xvs, xs, jnp.zeros((), xs.dtype)
                                        ).astype(dtype if fn == "sum"
                                                 else jnp.float64)
                        upto = _prefix_upto(acc, part_start_idx)
                        s = upto(fe, part_start_idx) - upto(fs - 1,
                                                            part_start_idx)
                        if fn == "sum":
                            res = s.astype(dtype)
                        else:
                            res = (s / jnp.maximum(cnt, 1)).astype(dtype)
                        val = cnt > 0
                elif fn in ("min", "max"):
                    # supported frames: start at partition/frame head
                    # (running) or whole partition / through UNBOUNDED
                    # FOLLOWING (reverse running).
                    op = jnp.minimum if fn == "min" else jnp.maximum
                    kind = np.dtype(xs.dtype).kind
                    if kind == "f":
                        sent = jnp.inf if fn == "min" else -jnp.inf
                    elif kind == "b":
                        sent = fn == "min"
                    else:
                        info = jnp.iinfo(xs.dtype)
                        sent = info.max if fn == "min" else info.min
                    acc = jnp.where(xvs, xs, jnp.full((), sent, xs.dtype))
                    run = _seg_scan(op, acc, part_start)
                    rev_run = jnp.flip(_seg_scan(
                        op, jnp.flip(acc), jnp.flip(part_last)))
                    unit, sk, _sv, ek, _ev = frame
                    if sk == "UNBOUNDED_PRECEDING" and ek != "UNBOUNDED_FOLLOWING":
                        res = run[jnp.clip(fe, 0, n - 1)]
                    elif ek == "UNBOUNDED_FOLLOWING" and sk != "UNBOUNDED_PRECEDING":
                        res = rev_run[jnp.clip(fs, 0, n - 1)]
                    elif sk == "UNBOUNDED_PRECEDING":
                        res = run[part_end_idx]
                    else:
                        raise NotImplementedError(
                            f"window {fn} over sliding frame {frame}")
                    cnt_upto = _prefix_upto(xvs.astype(jnp.int64),
                                            part_start_idx)
                    cnt = cnt_upto(fe, part_start_idx) - cnt_upto(
                        fs - 1, part_start_idx)
                    val = cnt > 0
                    res = jnp.where(val, res, jnp.zeros((), res.dtype)
                                    ).astype(dtype)
                else:
                    raise NotImplementedError(f"window function {fn}")

            # scatter back to input row order
            out_d = jnp.zeros((n,), res.dtype).at[perm].set(res)
            out_v = jnp.zeros((n,), jnp.bool_).at[perm].set(val)
            outs.append((out_d, out_v))
        return outs

    return program


def _frame_indices(frame, arange, part_start_idx, part_end_idx,
                   peer_start_idx, peer_end_idx):
    """(frame_start, frame_end) sorted indices per row (unclamped)."""
    unit, sk, sv, ek, ev = frame
    if unit == "RANGE":
        if sk in ("PRECEDING", "FOLLOWING") or ek in ("PRECEDING", "FOLLOWING"):
            raise NotImplementedError("RANGE frames with numeric offsets")
        cur_s, cur_e = peer_start_idx, peer_end_idx
    else:
        cur_s, cur_e = arange, arange
    if sk == "UNBOUNDED_PRECEDING":
        fs = part_start_idx
    elif sk == "CURRENT":
        fs = cur_s
    elif sk == "PRECEDING":
        fs = arange - sv
    elif sk == "FOLLOWING":
        fs = arange + sv
    else:
        raise NotImplementedError(f"frame start {sk}")
    if ek == "UNBOUNDED_FOLLOWING":
        fe = part_end_idx
    elif ek == "CURRENT":
        fe = cur_e
    elif ek == "FOLLOWING":
        fe = arange + ev
    elif ek == "PRECEDING":
        fe = arange - ev
    else:
        raise NotImplementedError(f"frame end {ek}")
    return fs, fe


def compute_windows(
    partition_keys: Sequence[tuple],  # [(data, valid|None), ...]
    order_keys: Sequence[tuple],  # [(data, valid|None, asc, nulls_first), ...]
    functions: Sequence[dict],
    num_rows: int,
) -> list[tuple[np.ndarray, Optional[np.ndarray]]]:
    """Evaluate window functions over one materialized input.

    ``functions``: per call a dict with keys ``fn``, ``args``
    ([(data, valid|None), ...]), ``offset`` (int; lag/lead/ntile/nth_value
    constant), ``frame`` ((unit, start_kind, start_val, end_kind, end_val)),
    ``dtype`` (output numpy dtype).  Returns per call (data, valid) in the
    ORIGINAL row order (device arrays).
    """
    n_part = len(partition_keys)
    part_valid = tuple(v is not None for _, v in partition_keys)
    order_spec = tuple(
        (v is not None, bool(asc), bool(nf)) for _, v, asc, nf in order_keys)
    fn_spec = []
    flat: list = []
    for d, v in partition_keys:
        flat.append(jnp.asarray(d))
        if v is not None:
            flat.append(jnp.asarray(v))
    for d, v, _asc, _nf in order_keys:
        flat.append(jnp.asarray(d))
        if v is not None:
            flat.append(jnp.asarray(v))
    for f in functions:
        args = f.get("args", [])
        arg_valid = tuple(v is not None for _, v in args)
        fn_spec.append((
            f["fn"], len(args), arg_valid, int(f.get("offset", 1)),
            tuple(f["frame"]), np.dtype(f["dtype"]).str,
        ))
        for d, v in args:
            flat.append(jnp.asarray(d))
            if v is not None:
                flat.append(jnp.asarray(v))
    program = _window_program(n_part, part_valid, order_spec, tuple(fn_spec))
    return program(jnp.zeros((num_rows,), jnp.int8), *flat)
