"""Operators: the batch-at-a-time data plane.

Mirrors Trino's operator contract (reference: operator/Operator.java:21 —
``needsInput``/``addInput``/``getOutput``/``isFinished``) with the same
streaming/blocking split:

- streaming: ScanOperator, FilterProjectOperator (the fused
  ScanFilterAndProjectOperator analogue — operator/
  ScanFilterAndProjectOperator.java:68), LookupJoinOperator
  (operator/join/LookupJoinOperator.java:37), LimitOperator.
- blocking (accumulate → finish → emit): HashAggregationOperator
  (operator/HashAggregationOperator.java:53), SortOperator/TopNOperator
  (operator/OrderByOperator.java:44, TopNOperator.java:34), JoinBuildSink
  (operator/join/HashBuilderOperator.java:57), DistinctLimitOperator.

The per-row compiled inner loops of the JVM design are replaced by the
jitted kernels in exec/kernels.py; operators are thin host-side glue that
moves fixed-shape column arrays in and out of those programs.
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.expr import compile_expression
from ..sql.analyzer import STAT_AGGS
from ..spi.batch import (Column, ColumnBatch, encoded_exec, pad_to_bucket,
                         unify_dictionaries)
from ..spi.errors import SUBQUERY_MULTIPLE_ROWS, TrinoError
from ..spi.connector import Connector, ConnectorPageSink, Split
from ..spi.types import BIGINT, BOOLEAN, DOUBLE, DecimalType, Type, is_string
from ..sql.ir import InputRef, RowExpression, referenced_inputs
from ..planner.plan import AggCall, SortKey, WindowFunc
from . import kernels as K
from . import syncguard as SG
from . import window_kernels as WK
from .prefetch import (
    BatchCoalescer,
    DeviceStager,
    IngestConfig,
    PrefetchingPageSource,
    encode_scan_batch,
)
from .stats import EncodingStats, ScanIngestStats

__all__ = [
    "Operator",
    "ScanOperator",
    "ValuesOperator",
    "LocalUnionBridge",
    "UnionSinkOperator",
    "UnionSourceOperator",
    "FilterProjectOperator",
    "plan_lazy_scan",
    "HashAggregationOperator",
    "JoinBridge",
    "JoinBuildSink",
    "LookupJoinOperator",
    "SemiJoinOperator",
    "SortOperator",
    "TopNOperator",
    "WindowOperator",
    "LimitOperator",
    "GroupIdOperator",
    "ReplicateOperator",
    "TableFunctionOperator",
    "UnnestOperator",
    "DistinctLimitOperator",
    "TableWriterOperator",
    "OutputCollector",
    "RenameOperator",
]


class Operator:
    """Synchronous single-driver operator protocol."""

    input_done: bool = False
    _closed: bool = False

    def needs_input(self) -> bool:
        return not self.input_done and not self._closed

    def add_input(self, batch: ColumnBatch) -> None:
        raise NotImplementedError

    def finish_input(self) -> None:
        self.input_done = True

    def get_output(self) -> Optional[ColumnBatch]:
        return None

    def is_finished(self) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        """Downstream no longer needs output (e.g. LIMIT satisfied)."""
        self._closed = True
        self.input_done = True


# ---------------------------------------------------------------------------
# sources


class ScanOperator(Operator):
    """Reads splits via the connector page source (operator/
    TableScanOperator.java:46).  ``dynamic_filters`` [(column_idx, holder)]
    prune rows before padding/device transfer (the probe side of
    DynamicFilterService — see exec/dynamic_filter.py).

    With ``TRINO_TPU_PREFETCH=1`` (the default) the scan runs the async
    ingest pipeline of exec/prefetch.py: splits decode on background
    threads into a bounded queue, small batches coalesce up to the target
    power-of-two bucket, and the next batch's ``jax.device_put`` is
    dispatched while the previous one computes downstream.  With
    ``TRINO_TPU_PREFETCH=0`` the synchronous one-split-at-a-time path below
    runs bit-for-bit as before."""

    def __init__(self, connector: Connector, splits: Sequence[Split],
                 columns: Sequence[str], dynamic_filters=None,
                 constraint=None, limit: Optional[int] = None):
        self.connector = connector
        self.splits = list(splits)
        self.columns = list(columns)
        self.dynamic_filters = list(dynamic_filters or [])
        # pushed-down LIMIT: stop opening further splits once this many
        # rows are out (only exact for unmasked batches; the engine Limit
        # above re-enforces the precise count)
        self.limit = limit
        self._emitted_rows = 0
        # advisory TupleDomain from predicate pushdown (exec/domain_filter.py)
        self.constraint = constraint if (
            constraint is not None and not constraint.is_all) else None
        self._name_to_idx = {n: i for i, n in enumerate(self.columns)}
        self._domain_dict_cache: dict = {}
        self.rows_pruned_by_domain = 0
        self._source = None
        self.input_done = True
        # -- async ingest state (exec/prefetch.py) --
        self.ingest_cfg = IngestConfig.from_env()
        self.ingest_stats = ScanIngestStats()
        # compressed execution: channels the downstream FilterProject only
        # passes through (set by plan_lazy_scan) stage as LAZY columns —
        # their bytes cross to the device only if something touches them
        self.lazy_channels: frozenset[int] = frozenset()
        self.encoding_stats = EncodingStats()
        self._prefetcher: Optional[PrefetchingPageSource] = None
        self._coalescer: Optional[BatchCoalescer] = None
        self._stager: Optional[DeviceStager] = None
        self._staged: Optional[ColumnBatch] = None
        self._hold_back: Optional[ColumnBatch] = None
        self._ingest_done = False

    def needs_input(self) -> bool:
        return False

    def _apply_constraint(self, batch: ColumnBatch) -> ColumnBatch:
        from .domain_filter import tuple_domain_mask

        mask = tuple_domain_mask(batch, self.constraint, self._name_to_idx,
                                 self._domain_dict_cache)
        if mask is None or mask.all():
            return batch
        self.rows_pruned_by_domain += int(batch.num_rows - mask.sum())
        return batch.filter(mask)

    def _apply_dynamic_filters(self, batch: ColumnBatch) -> ColumnBatch:
        mask = None
        for col_idx, holder in self.dynamic_filters:
            c = batch.columns[col_idx]
            m = holder.probe_mask(c.data, c.valid, c.dictionary)
            if m is not None:
                mask = m if mask is None else (mask & m)
        if mask is None or mask.all():
            return batch
        for _, holder in self.dynamic_filters:
            holder.rows_pruned += int(batch.num_rows - mask.sum())
            break  # credit once per batch
        return batch.filter(mask)

    def get_output(self) -> Optional[ColumnBatch]:
        if self.ingest_cfg.enabled:
            return self._get_output_async()
        return self._get_output_sync()

    def _get_output_sync(self) -> Optional[ColumnBatch]:
        while True:
            if self._closed:
                return None
            if self._source is None:
                if (self.limit is not None
                        and self._emitted_rows >= self.limit):
                    # pushed-down LIMIT satisfied: drop remaining splits
                    self.splits = []
                if not self.splits:
                    return None
                # kwarg only when constrained: wrapper connectors with the
                # bare (split, columns) signature keep working
                if self.constraint is not None:
                    self._source = self.connector.create_page_source(
                        self.splits.pop(0), self.columns,
                        constraint=self.constraint)
                else:
                    self._source = self.connector.create_page_source(
                        self.splits.pop(0), self.columns)
            if self._source.is_finished():
                self._source.close()
                self._source = None
                continue
            batch = self._source.get_next_batch()
            if batch is not None:
                # device-pinned batches (live mask set) skip host-side
                # dynamic filtering — pulling them down would cost more
                # than the pruning saves
                if self.constraint is not None and batch.live is None:
                    batch = self._apply_constraint(batch)
                    if batch.num_rows == 0:
                        continue
                if self.dynamic_filters and batch.live is None:
                    batch = self._apply_dynamic_filters(batch)
                    if batch.num_rows == 0:
                        continue
                # bucket scan output shapes so every downstream jitted
                # program compiles once per (pipeline, bucket)
                if self.limit is not None and batch.live is None:
                    self._emitted_rows += batch.num_rows
                self.ingest_stats.observe_batch(batch.nbytes, batch.num_rows)
                if encoded_exec():
                    batch = encode_scan_batch(
                        batch, self.lazy_channels, self.encoding_stats)
                return pad_to_bucket(batch)

    # -- async ingest path --------------------------------------------------

    def _ensure_ingest(self) -> None:
        if self._prefetcher is not None or self._ingest_done:
            return
        self._prefetcher = PrefetchingPageSource(
            self.connector, self.splits, self.columns,
            constraint=self.constraint, config=self.ingest_cfg,
            stats=self.ingest_stats, limit_rows=self.limit)
        self.splits = []  # owned by the prefetcher now
        self._coalescer = BatchCoalescer(
            self.ingest_cfg.coalesce_rows, stats=self.ingest_stats)
        self._stager = DeviceStager(stats=self.ingest_stats,
                                    lazy_channels=self.lazy_channels,
                                    enc_stats=self.encoding_stats)

    def _stage(self, batch: ColumnBatch) -> ColumnBatch:
        if self.ingest_cfg.stage_device:
            from ..telemetry import profiler

            if profiler.enabled():
                t0 = profiler.now()
                staged = self._stager.stage(batch)
                profiler.event(profiler.STAGE, "scan.stage", t0,
                               rows=batch.num_rows, bytes=batch.nbytes)
                return staged
            return self._stager.stage(batch)
        return batch

    def _produce_next(self) -> Optional[ColumnBatch]:
        """One coalesced+staged batch, or None at end of input.  Filters run
        consumer-side (holder counters are not thread-safe); a device-pinned
        batch (``live`` set) flushes the coalescer first so row order holds,
        then passes through like the sync path."""
        if self._ingest_done:
            return None
        self._ensure_ingest()
        while True:
            if (self.limit is not None
                    and self._emitted_rows >= self.limit):
                # pushed-down LIMIT satisfied: abort prefetch, flush tail
                self._prefetcher.close()
                self._ingest_done = True
                flushed = self._coalescer.flush()
                return None if flushed is None else self._stage(flushed)
            batch = self._prefetcher.get_next_batch()
            if batch is None:
                self._ingest_done = True
                flushed = self._coalescer.flush()
                return None if flushed is None else self._stage(flushed)
            if batch.live is not None:
                flushed = self._coalescer.flush()
                if flushed is not None:
                    self._hold_back = pad_to_bucket(batch)
                    return self._stage(flushed)
                return pad_to_bucket(batch)
            if self.constraint is not None:
                batch = self._apply_constraint(batch)
            if self.dynamic_filters:
                batch = self._apply_dynamic_filters(batch)
            if batch.num_rows == 0:
                continue
            if self.limit is not None:
                self._emitted_rows += batch.num_rows
            self._coalescer.add(batch)
            if self._coalescer.ready():
                return self._stage(self._coalescer.flush())

    def _get_output_async(self) -> Optional[ColumnBatch]:
        if self._closed:
            return None
        if self._hold_back is not None:
            out, self._hold_back = self._hold_back, None
        elif self._staged is not None:
            out, self._staged = self._staged, None
        else:
            out = self._produce_next()
        # double buffering: dispatch the next batch's device transfer now so
        # it overlaps downstream compute on `out`
        if out is not None and self._staged is None \
                and self._hold_back is None:
            self._staged = self._produce_next()
        return out

    def is_finished(self) -> bool:
        if self._closed:
            return True
        if not self.ingest_cfg.enabled:
            return self._source is None and not self.splits
        if self._staged is not None or self._hold_back is not None:
            return False
        if self._prefetcher is None:
            return self._ingest_done or not self.splits
        return self._ingest_done and self._coalescer.buffered_rows == 0

    def close(self) -> None:
        super().close()
        if self._prefetcher is not None:
            self._prefetcher.close()  # drop in-flight + unclaimed splits


class TableFunctionOperator(Operator):
    """Leaf table-function source (reference:
    operator/LeafTableFunctionOperator.java:41): drains the bound
    function's batch generator."""

    def __init__(self, bound, output_names):
        self.output_names = list(output_names)
        self._iter = bound.batches()
        self._done = False
        self.input_done = True

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[ColumnBatch]:
        if self._done or self._closed:
            return None
        batch = next(self._iter, None)
        if batch is None:
            self._done = True
            return None
        return pad_to_bucket(batch.rename(self.output_names))

    def is_finished(self) -> bool:
        return self._done or self._closed


class ValuesOperator(Operator):
    def __init__(self, batch: ColumnBatch):
        self._batch = batch
        self.input_done = True

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[ColumnBatch]:
        b, self._batch = self._batch, None
        return b

    def is_finished(self) -> bool:
        return self._batch is None


# ---------------------------------------------------------------------------
# union (local gather between pipelines)


class LocalUnionBridge:
    """In-task handoff for Union inputs: each input pipeline ends in a
    UnionSinkOperator appending here; the consumer pipeline starts from a
    UnionSourceOperator.  The single-driver analogue of a gathering
    LocalExchange (reference: operator/exchange/LocalExchange.java:67)."""

    def __init__(self, num_inputs: int):
        from collections import deque

        self.num_inputs = num_inputs
        self.batches: "deque[ColumnBatch]" = deque()
        self.finished_inputs = 0
        self._lock = threading.Lock()  # sinks may run on concurrent drivers
        # True only for task_concurrency source forks: the driver runner
        # threads sibling chains for these (plain UNION branches may hold
        # memory-accounted operators that assume one thread)
        self.concurrent = False

    def input_finished(self) -> None:
        with self._lock:
            self.finished_inputs += 1

    @property
    def all_finished(self) -> bool:
        return self.finished_inputs >= self.num_inputs


class UnionSinkOperator(Operator):
    def __init__(self, bridge: LocalUnionBridge, names: Sequence[str]):
        self.bridge = bridge
        self.names = list(names)

    def add_input(self, batch: ColumnBatch) -> None:
        if batch.num_rows:
            self.bridge.batches.append(batch.rename(self.names))

    def finish_input(self) -> None:
        super().finish_input()
        self.bridge.input_finished()

    def is_finished(self) -> bool:
        return self.input_done


class UnionSourceOperator(Operator):
    def __init__(self, bridge: LocalUnionBridge):
        self.bridge = bridge
        self.input_done = True

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[ColumnBatch]:
        if self._closed or not self.bridge.all_finished:
            return None
        if self.bridge.batches:
            return self.bridge.batches.popleft()
        return None

    def is_finished(self) -> bool:
        return self._closed or (self.bridge.all_finished
                                and not self.bridge.batches)


# ---------------------------------------------------------------------------
# filter + project (the jit-fusion point)


def _to_cols(batch: ColumnBatch):
    """(data, valid) pairs, device-passthrough: jax arrays stay on device."""
    return [(c.data, c.valid) for c in batch.columns]


class FilterProjectOperator(Operator):
    """Fused filter+project compiled to ONE jitted XLA program per
    (expression set, shape bucket): the predicate ANDs into the batch's
    ``live`` selection mask instead of compacting (dynamic shapes defeat
    XLA), projections evaluate on every lane, and columns stay device-
    resident between operators.  Replaces sql/gen/PageFunctionCompiler.java:
    104 bytecode + operator/ScanFilterAndProjectOperator.java:68 fusion."""

    # Cross-execution program cache: operators are rebuilt per query run, but
    # the jitted XLA program depends only on (expressions, input types,
    # dictionaries, output dtypes).  jax.jit caches by function identity, so
    # a fresh closure per run would recompile every time (~0.5-0.8s per
    # program on a tunneled TPU).  Values hold their dictionary arrays so the
    # id()-based key component can never be recycled by the allocator.
    # Guarded by a lock: distributed worker threads share this cache.
    _PROGRAM_CACHE: dict = {}
    _PROGRAM_CACHE_LOCK = threading.Lock()

    def __init__(self, predicate: Optional[RowExpression],
                 projections: Optional[Sequence[RowExpression]],
                 output_names: Sequence[str], output_types: Sequence[Type]):
        self.predicate = predicate
        self.projections = list(projections) if projections is not None else None
        self.output_names = list(output_names)
        self.output_types = list(output_types)
        self._pending: Optional[ColumnBatch] = None
        self._compiled = None
        self._compiled_dicts = None
        # device int32 scalars, one per batch whose program traced an
        # error-capable op (division, overflow...); drained by the runner
        self.pending_errors: list = []
        self.encoding_stats = EncodingStats()

    def _compile(self, batch: ColumnBatch):
        dicts = [c.dictionary for c in batch.columns]
        if self._compiled is not None and all(
            a is b for a, b in zip(self._compiled_dicts, dicts)
        ):
            return self._compiled
        types = [c.type for c in batch.columns]
        key = (
            self.predicate,
            None if self.projections is None else tuple(self.projections),
            tuple(types),
            tuple(id(d) if d is not None else None for d in dicts),
            tuple(self.output_types),
        )
        cache = FilterProjectOperator._PROGRAM_CACHE
        with FilterProjectOperator._PROGRAM_CACHE_LOCK:
            hit = cache.get(key)
            if hit is not None:
                self._compiled, self._compiled_dicts = hit[0], dicts
                return self._compiled
            if len(cache) >= 1024:  # bound: evict oldest (insertion order)
                cache.pop(next(iter(cache)))
        pred = (
            compile_expression(self.predicate, types, dicts)
            if self.predicate is not None
            else None
        )
        projs = (
            [compile_expression(e, types, dicts) for e in self.projections]
            if self.projections is not None
            else None
        )
        out_dtypes = [t.storage_dtype for t in self.output_types]

        def run(cols, live):
            from ..ops.expr import (
                expr_condition_mask,
                expr_error_scope,
                reduce_error_lanes,
            )

            n = cols[0][0].shape[0]
            with expr_error_scope() as errs:
                if pred is not None:
                    with expr_condition_mask(live):
                        data, valid = pred(cols)
                    mask = data if valid is None else data & valid
                    if getattr(mask, "ndim", 1) == 0:
                        mask = jnp.broadcast_to(mask, (n,))
                    live = mask if live is None else live & mask
                if projs is None:
                    outs = [(d, v) for d, v in cols]
                else:
                    outs = []
                    with expr_condition_mask(live):
                        for ce, dt in zip(projs, out_dtypes):
                            d, v = ce(cols)
                            d = jnp.asarray(d)
                            if d.ndim == 0:
                                d = jnp.broadcast_to(d, (n,))
                            d = d.astype(dt)
                            if v is not None:
                                v = jnp.asarray(v)
                                if v.ndim == 0:
                                    v = jnp.broadcast_to(v, (n,))
                            outs.append((d, v))
                err = reduce_error_lanes(errs, (n,))
            # one int32 scalar (or None when nothing error-capable was
            # traced); each recording already carries its lane mask (input
            # live for the predicate, post-filter live for projections), so
            # a filtered-out row can't raise but a failing WHERE clause can
            err_code = None if err is None else jnp.max(err)
            return outs, live, err_code

        self._compiled = (jax.jit(run), projs)
        self._compiled_dicts = dicts
        with FilterProjectOperator._PROGRAM_CACHE_LOCK:
            FilterProjectOperator._PROGRAM_CACHE.setdefault(
                key, (self._compiled, dicts))
        return self._compiled

    def needs_input(self) -> bool:
        return self._pending is None and super().needs_input()

    def _encoded_plan(self, batch: ColumnBatch):
        """(needed_channels, passthrough) for the encoded fast path, or
        None to use the legacy all-channels path.

        ``needed`` are input channels the compiled program actually reads
        (predicate inputs + every non-trivial projection's inputs); they
        feed the jit as real arrays, materializing LAZY / expanding RLE
        on device.  ``passthrough`` maps output position -> input channel
        for bare InputRef projections, whose columns bypass the program
        entirely and KEEP their encoding — this is the late-
        materialization seam: a selective predicate only ever touches its
        own channels, and payload columns ride through still encoded."""
        needed: set[int] = set()
        if self.predicate is not None:
            needed |= referenced_inputs(self.predicate)
        passthrough: dict[int, int] = {}
        if self.projections is None:
            # pure filter: every column passes through positionally
            passthrough = {i: i for i in range(batch.num_columns)}
        else:
            for j, e in enumerate(self.projections):
                if (isinstance(e, InputRef)
                        and str(batch.columns[e.index].type)
                        == str(self.output_types[j])):
                    passthrough[j] = e.index
                else:
                    needed |= referenced_inputs(e)
        if any(i >= batch.num_columns for i in needed):
            return None  # malformed ref; let the legacy path raise
        return needed, passthrough

    def _add_input_encoded(self, batch: ColumnBatch) -> bool:
        """Encoding-aware filter+project: compute the mask from needed
        channels only; RLE/LAZY columns that merely pass through are never
        expanded or staged.  Returns False to fall back to legacy."""
        plan = self._encoded_plan(batch)
        if plan is None:
            return False
        needed, passthrough = plan
        batch = pad_to_bucket(batch)
        n = batch.num_rows
        cols_in = []
        for i, c in enumerate(batch.columns):
            if i in needed:
                if c.encoding == "RLE":
                    cols_in.append((K.rle_fill(c.rle_value, n), c.valid))
                else:  # touching .data materializes LAZY exactly once
                    cols_in.append((c.data, c.valid))
            else:
                # dead placeholder: device-created zeros cost no PCIe and
                # XLA removes the unused input from the program
                dtype = (np.int32 if c.dictionary is not None
                         else c.type.storage_dtype)
                cols_in.append((jnp.zeros(n, dtype), None))
        run, projs = self._compile(batch)
        outs, live, err_code = run(cols_in, batch.live)
        if err_code is not None:
            self.pending_errors.append(err_code)
        cols = []
        if projs is None:
            for i, ((d, v), c) in enumerate(zip(outs, batch.columns)):
                if i in passthrough:
                    cols.append(c)
                else:
                    cols.append(Column(c.type, d, v, c.dictionary))
        else:
            for j, ((d, v), t, ce) in enumerate(
                    zip(outs, self.output_types, projs)):
                if j in passthrough:
                    cols.append(batch.columns[passthrough[j]])
                else:
                    cols.append(Column(t, d, v, ce.dictionary))
        self._observe_encoded(batch, needed)
        self._pending = ColumnBatch(self.output_names, cols, live)
        return True

    def _observe_encoded(self, batch: ColumnBatch, needed: set[int]) -> None:
        es = self.encoding_stats
        saved = 0
        n_rle = n_dict = 0
        for i, c in enumerate(batch.columns):
            enc = c.encoding
            if enc == "RLE":
                n_rle += 1
            elif enc == "DICT":
                n_dict += 1
            if enc in ("RLE", "LAZY") and i not in needed:
                saved += c.flat_nbytes - c.nbytes
        if n_rle:
            es.rle_batches += 1
        if n_dict:
            es.dict_batches += 1
        if saved > 0:
            es.bytes_saved += saved

    def add_input(self, batch: ColumnBatch) -> None:
        if batch.num_columns == 0:
            self._pending = batch.rename(self.output_names)
            return
        if (encoded_exec()
                and any(c.encoding in ("RLE", "LAZY") for c in batch.columns)
                and self._add_input_encoded(batch)):
            return
        batch = pad_to_bucket(batch)
        run, projs = self._compile(batch)
        outs, live, err_code = run(_to_cols(batch), batch.live)
        if err_code is not None:
            # device scalar; checked in ONE batched fetch at pipeline end
            # (run_pipelines -> ops.expr.check_error_scalars)
            self.pending_errors.append(err_code)
        if projs is None:
            cols = [Column(c.type, d, v, c.dictionary)
                    for (d, v), c in zip(outs, batch.columns)]
        else:
            cols = [Column(t, d, v, ce.dictionary)
                    for (d, v), t, ce in zip(outs, self.output_types, projs)]
        self._pending = ColumnBatch(self.output_names, cols, live)

    def get_output(self) -> Optional[ColumnBatch]:
        b, self._pending = self._pending, None
        return b

    def is_finished(self) -> bool:
        return self.input_done and self._pending is None


def plan_lazy_scan(pipeline: Sequence[Operator]) -> None:
    """Late-materialization planning: when a scan feeds straight into a
    filtering FilterProject, every channel the filter only passes through
    stages as LAZY — the mask computes from predicate columns alone, and a
    selective filter's payload bytes never cross to the device (the
    LazyBlock contract of ScanFilterAndProjectOperator).  Called once per
    pipeline at local-planning time; a no-op unless TRINO_TPU_ENCODED_EXEC
    allows encoded execution."""
    if not encoded_exec() or len(pipeline) < 2:
        return
    scan, fp = pipeline[0], pipeline[1]
    if not (isinstance(scan, ScanOperator)
            and isinstance(fp, FilterProjectOperator)
            and fp.predicate is not None):
        return
    needed = set(referenced_inputs(fp.predicate))
    if fp.projections is not None:
        for e in fp.projections:
            if not isinstance(e, InputRef):
                needed |= referenced_inputs(e)
    scan.lazy_channels = frozenset(
        i for i in range(len(scan.columns)) if i not in needed)


class RenameOperator(Operator):
    def __init__(self, names: Sequence[str]):
        self.names = list(names)
        self._pending = None

    def needs_input(self) -> bool:
        return self._pending is None and super().needs_input()

    def add_input(self, batch: ColumnBatch) -> None:
        self._pending = batch.rename(self.names)

    def get_output(self):
        b, self._pending = self._pending, None
        return b

    def is_finished(self) -> bool:
        return self.input_done and self._pending is None


# ---------------------------------------------------------------------------
# memory-accounted input buffering (the revocable-memory participants)


_COMPACT_FACTOR = 4  # compact when live rows < lanes/4
_COMPACT_MIN_LANES = 1 << 16  # below this a count sync costs more than it saves


def _sync_free() -> bool:
    """Sync-free probe/expand hot loop (default on): joins pick padded
    expand capacities from build-side statistics and defer overflow checks
    to async flag polls, so steady-state probe batches cross the device
    boundary zero times.  ``TRINO_TPU_SYNC_FREE=0`` restores the legacy
    one-scalar-sync-per-batch paths (equivalence tests, triage)."""
    return os.environ.get("TRINO_TPU_SYNC_FREE", "1") != "0"


def _maybe_compact_device(batch: ColumnBatch) -> ColumnBatch:
    """Shrink a sparsely-live device batch to bucket(live) lanes before
    O(lanes log lanes) work.  A selective join keeps its probe batch's fat
    static shape (the sync-free contract of join_exec.run_unique); paying ONE
    live-count sync here stops those dead lanes from riding through every
    downstream sort.  Host batches and dense batches pass through."""
    live = batch.live
    if live is None or isinstance(live, np.ndarray):
        return batch
    n = batch.num_rows
    if n < _COMPACT_MIN_LANES:
        return batch
    count = int(SG.fetch(jnp.sum(jnp.asarray(live)), "exec.compact-count"))
    if count * _COMPACT_FACTOR <= n:
        return K.compact_device_batch(batch, count)
    return batch


class BufferedInputMixin:
    """Blocking operators accumulate ``self._batches``; with a
    TaskMemoryContext attached (exec/revoking.py) the buffered DEVICE bytes
    are reserved as revocable HBM and evicted to host RAM on revoke."""

    _mem = None  # TaskMemoryContext, set via attach_memory

    def attach_memory(self, mem) -> None:
        self._mem = mem
        if mem is not None:
            mem.register(self)

    def account_memory(self) -> None:
        if self._mem is not None:
            from .revoking import batch_device_residual

            self._mem.update(self, batch_device_residual(self))
            self._maybe_spill_to_disk()

    def revoke_memory(self) -> int:
        from .revoking import batch_device_nbytes

        freed = 0
        batches = getattr(self, "_batches", [])
        for i, b in enumerate(batches):
            d = batch_device_nbytes(b)
            if d:
                batches[i] = b.to_host()
                freed += d
        if freed:
            self.spill_count = getattr(self, "spill_count", 0) + 1
        return freed

    def release_memory(self) -> None:
        """Drop the input buffer + its reservation after finish consumes it
        (a lingering reservation would trigger pointless spills of dead
        buffers in later operators sharing the pool)."""
        self._batches = []
        if self._mem is not None:
            self._mem.update(self, 0)

    def _maybe_spill_to_disk(self) -> None:
        """Third tier: buffered batches exceeding the session's disk
        threshold go to a serde spill file (exec/spill.py).  Device-staged
        batches count toward the threshold too — a disk limit is an explicit
        request for bounded buffering, so they evict to host on the way down
        (otherwise async-ingest scans would route every batch around this
        tier as device arrays)."""
        limit = getattr(self._mem, "spill_to_disk_bytes", 0) if self._mem else 0
        if not limit:
            return
        batches = getattr(self, "_batches", None)
        if not batches or not batches[0].columns:
            return
        if sum(b.nbytes for b in batches) <= limit:
            return
        from .spill import Spiller

        if getattr(self, "_spiller", None) is None:
            self._spiller = Spiller()
        for b in batches:
            if not isinstance(b.columns[0].data, np.ndarray):
                b = b.to_host()
            self._spiller.spill(b)
        self._batches = []

    def buffered_batches(self) -> list:
        """The operator's full input: disk-spilled pages restored first,
        then the in-memory tail (finish-time accessor)."""
        spiller = getattr(self, "_spiller", None)
        if spiller is not None:
            restored = list(spiller.read_back())
            spiller.close()
            self._spiller = None
            self._batches = restored + self._batches
        return self._batches


# ---------------------------------------------------------------------------
# aggregation


def _round_half_up_div_int(s: np.ndarray, c: np.ndarray) -> np.ndarray:
    q = (2 * np.abs(s) + c) // (2 * c)
    return np.where(s < 0, -q, q)


def _concat_device(batches: Sequence[ColumnBatch]) -> ColumnBatch:
    """Concatenate (possibly masked) batches on device, padded to the
    total's power-of-two bucket.  Dead/padding rows are carried in ``live``
    so the result has a cache-friendly static shape — this is how blocking
    operators materialize input without leaving the device."""
    names = batches[0].names
    total = sum(b.num_rows for b in batches)
    cap = K.bucket(total)
    pad = cap - total
    any_live = pad > 0 or any(b.live is not None for b in batches)
    out_cols = []
    for i in range(len(names)):
        cs = [b.columns[i] for b in batches]
        if cs[0].type.is_dictionary_encoded:
            cs = unify_dictionaries(cs)
        # RLE runs expand with a device-side fill: one scalar crosses the
        # host boundary instead of the whole run
        parts = [K.rle_fill(c.rle_value, len(c)) if c.encoding == "RLE"
                 else jnp.asarray(c.data) for c in cs]
        if pad:
            parts.append(jnp.zeros(pad, parts[0].dtype))
        data = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        valid = None
        if any(c.valid is not None for c in cs):
            vparts = [
                jnp.asarray(c.valid) if c.valid is not None
                else jnp.ones(len(c), jnp.bool_)
                for c in cs
            ]
            if pad:
                vparts.append(jnp.zeros(pad, jnp.bool_))
            valid = jnp.concatenate(vparts) if len(vparts) > 1 else vparts[0]
        out_cols.append(Column(cs[0].type, data, valid, cs[0].dictionary))
    live = None
    if any_live:
        lparts = [
            jnp.asarray(b.live) if b.live is not None
            else jnp.ones(b.num_rows, jnp.bool_)
            for b in batches
        ]
        if pad:
            lparts.append(jnp.zeros(pad, jnp.bool_))
        live = jnp.concatenate(lparts) if len(lparts) > 1 else lparts[0]
    return ColumnBatch(names, out_cols, live)


class HashAggregationOperator(BufferedInputMixin, Operator):
    """Grouped aggregation: accumulate batches, then sort-based segment
    reduction (replaces operator/HashAggregationOperator.java:53 +
    FlatHash.java:42 with the kernels in exec/kernels.py).

    PARTIAL steps flush early: when the buffered input exceeds
    ``flush_rows``, the accumulated batches are pre-aggregated and emitted
    immediately (states are mergeable by FINAL), so a worker's memory stays
    bounded by the flush window rather than its whole input — the
    InMemoryHashAggregationBuilder partial-flush behavior
    (operator/aggregation/builder/InMemoryHashAggregationBuilder.java)."""

    FLUSH_ROWS = 1 << 20
    SPILL_PARTITIONS = 16

    def __init__(self, group_keys: Sequence[int], aggs: Sequence[AggCall],
                 output_names: Sequence[str], output_types: Sequence[Type],
                 step: str = "SINGLE"):
        self.group_keys = list(group_keys)
        self.aggs = list(aggs)
        self.output_names = list(output_names)
        self.output_types = list(output_types)
        self.step = step
        self._batches: list[ColumnBatch] = []
        self._buffered_rows = 0
        self._flushed: list[ColumnBatch] = []
        self._result: Optional[ColumnBatch] = None
        self._emitted = False
        self.encoding_stats = EncodingStats()
        # partitioned state spill (SpillableHashAggregationBuilder.java):
        # one spill file per hash partition of pre-aggregated states
        self._state_spillers: Optional[list] = None
        self._spill_layout = None  # (p_names, p_types, final_calls)

    # -- partitioned state spill -------------------------------------------
    def _spill_eligible(self) -> bool:
        return (self.step in ("SINGLE", "FINAL")
                and not any(a.distinct for a in self.aggs))

    def _maybe_spill_to_disk(self) -> None:
        """Disk tier override: instead of dumping RAW input pages, pre-
        aggregate the buffer into mergeable partial states, hash-partition
        them by group key, and append each partition to its own spill file
        (reference: operator/aggregation/builder/
        SpillableHashAggregationBuilder.java — spill states, merge on
        unspill, memory bounded by the largest partition)."""
        limit = getattr(self._mem, "spill_to_disk_bytes", 0) if self._mem else 0
        if not limit or not self._batches:
            return
        if not self._spill_eligible():
            super()._maybe_spill_to_disk()  # raw-page fallback (distinct)
            return
        host_bytes = sum(
            b.nbytes for b in self._batches
            if b.columns and isinstance(b.columns[0].data, np.ndarray))
        device_rows = sum(
            b.num_rows for b in self._batches
            if b.columns and not isinstance(b.columns[0].data, np.ndarray))
        if host_bytes <= limit and device_rows * 8 <= limit:
            return
        self._spill_states()

    def _ensure_spill_layout(self):
        if self._spill_layout is not None:
            return self._spill_layout
        from ..planner.add_exchanges import partial_agg_layout

        nk = len(self.group_keys)
        if self.step == "FINAL":
            # input IS already a state layout: spill rows pass through and
            # merge with the operator's own call list
            self._spill_layout = (None, None, list(self.aggs))
            return self._spill_layout
        layouts = partial_agg_layout(self.aggs, None)
        p_names = [f"k{i}" for i in range(nk)]
        p_types: list = [None] * nk  # filled from input at first spill
        f_calls = []
        ch = nk
        for a, states in zip(self.aggs, layouts):
            f_calls.append(AggCall(a.fn, ch, a.type, False))
            for j, (fn, t) in enumerate(states):
                p_names.append(f"s{ch}_{j}")
                p_types.append(t)
            ch += len(states)
        self._spill_layout = (p_names, p_types, f_calls)
        return self._spill_layout

    def _partial_state_batch(self) -> ColumnBatch:
        """Pre-aggregate the current buffer into mergeable partial states
        (or pass state rows through under FINAL)."""
        if self.step == "FINAL":
            return ColumnBatch.concat(self._batches)
        p_names, p_types, _ = self._ensure_spill_layout()
        tmp = HashAggregationOperator(
            self.group_keys, self.aggs, p_names,
            self._partial_types(), "PARTIAL")
        tmp._batches = self._batches
        return tmp._compute().compact()

    def _partial_types(self) -> list:
        """Concrete partial-state types (keys from the buffered input)."""
        p_names, p_types, _ = self._ensure_spill_layout()
        inp = self._batches[0]
        nk = len(self.group_keys)
        key_types = [inp.columns[c].type for c in self.group_keys]
        return key_types + [t for t in p_types[nk:]]

    def _spill_states(self) -> None:
        from .spill import Spiller
        from ..execution.task import _partition_key_tuple

        state = self._partial_state_batch()
        if self._state_spillers is None:
            self._state_spillers = [Spiller()
                                    for _ in range(self.SPILL_PARTITIONS)]
        nk = len(self.group_keys)
        if nk:
            keys = [_partition_key_tuple(state.columns[c])
                    for c in range(nk)]
            parts = K.partition_assignments(keys, self.SPILL_PARTITIONS)
        else:
            parts = np.zeros(state.num_rows, np.int32)
        for p in range(self.SPILL_PARTITIONS):
            sub = state.filter(parts == p)
            if sub.num_rows:
                self._state_spillers[p].spill(sub)
        self._batches = []
        self._buffered_rows = 0
        self.spill_count = getattr(self, "spill_count", 0) + 1
        if self._mem is not None:
            self._mem.update(self, 0)

    def _merge_spilled(self) -> list[ColumnBatch]:
        """Per-partition merge of spilled states (merge-on-unspill): memory
        is bounded by one partition's states at a time."""
        _, _, f_calls = self._ensure_spill_layout()
        nk = len(self.group_keys)
        outs: list[ColumnBatch] = []
        for sp in self._state_spillers:
            batches = list(sp.read_back())
            sp.close()
            if not batches:
                continue
            merger = HashAggregationOperator(
                list(range(nk)), f_calls, self.output_names,
                self.output_types, "FINAL")
            merger._batches = batches
            out = merger._compute()
            if out.num_rows:
                outs.append(out)
        self._state_spillers = None
        return outs

    def _can_flush(self) -> bool:
        # PARTIAL states merge downstream; SINGLE/FINAL must see all input.
        # (distinct never reaches PARTIAL — AddExchanges routes it SINGLE.)
        return self.step == "PARTIAL" and bool(self.group_keys)

    def add_input(self, batch: ColumnBatch) -> None:
        if batch.num_rows:
            self._batches.append(batch)
            self._buffered_rows += batch.num_rows
            if self._can_flush() and self._buffered_rows >= self.FLUSH_ROWS:
                out = self._compute()
                if out.num_rows:
                    self._flushed.append(out)
                self._batches = []
                self._buffered_rows = 0
            self.account_memory()

    def _agg_spec(self, a: AggCall, inp: ColumnBatch, out_t: Type):
        """kernel (fn, data, valid, dtype, distinct) for one AggCall."""
        if a.fn == "count" and a.arg < 0:
            return ("count_star", None, None, np.int64, False)
        col = inp.columns[a.arg]
        data, valid = col.data, col.valid
        if a.fn == "avg":
            # decomposes into sum+count; dtype promotes to f64 on device
            return ("avg", data, valid, np.float64, a.distinct)
        if a.fn in STAT_AGGS:
            # decomposes into (sum, sum-of-squares, count) states
            return (a.fn, data, valid, np.float64, a.distinct)
        if a.fn == "sum":
            if out_t == DOUBLE:
                dtype = np.float64
            elif out_t.name == "real":
                dtype = np.float32  # f32 lanes: the pallas fast path
            else:
                dtype = np.int64
            return ("sum", data, valid, dtype, a.distinct)
        if a.fn == "count":
            return ("count", data, valid, np.int64, a.distinct)
        return (a.fn, data, valid, data.dtype, a.distinct)

    def finish_input(self) -> None:
        super().finish_input()
        if self._state_spillers is not None:
            # flush the tail, then merge partition-by-partition (memory
            # bounded by the largest partition, not the whole input)
            if self._batches:
                self._spill_states()
            self._flushed.extend(self._merge_spilled())
            self._result = None
            self._emitted = True
            self.release_memory()
            return
        if self._flushed and not self._batches:
            self._result = None  # everything already emitted via flushes
            self._emitted = True
            self.release_memory()
            return
        self._result = self._compute()
        self.release_memory()

    def _empty_result(self, nk: int) -> ColumnBatch:
        if nk:  # grouped agg over empty input -> empty result
            cols = [Column(t, np.empty(0, t.storage_dtype))
                    for t in self.output_types]
            return ColumnBatch(self.output_names, cols)
        # global agg over empty input -> one row of defaults
        cols = []
        i = 0
        for a in self.aggs:
            if self.step == "PARTIAL" and a.fn == "avg":
                cols.append(Column(self.output_types[i],
                                   np.zeros(1, np.float64), np.zeros(1, bool)))
                cols.append(Column(self.output_types[i + 1], np.zeros(1, np.int64)))
                i += 2
                continue
            if self.step == "PARTIAL" and a.fn in STAT_AGGS:
                cols.append(Column(self.output_types[i],
                                   np.zeros(1, np.float64), np.zeros(1, bool)))
                cols.append(Column(self.output_types[i + 1], np.zeros(1, np.float64)))
                cols.append(Column(self.output_types[i + 2], np.zeros(1, np.int64)))
                i += 3
                continue
            t = self.output_types[i]
            i += 1
            if a.fn == "count":
                cols.append(Column(t, np.zeros(1, np.int64)))
            else:
                cols.append(Column(t, np.zeros(1, t.storage_dtype),
                                   np.zeros(1, bool)))
        return ColumnBatch(self.output_names, cols)

    # RLE-aware aggregation: fns computable arithmetically from one stored
    # value + a live/valid count, without ever expanding the run
    _RLE_AGG_FNS = frozenset(("sum", "count", "count_star", "min", "max"))

    def _rle_fast_path(self) -> Optional[ColumnBatch]:
        """Global aggregation over RLE inputs: SUM(x) over a constant run
        is ``value * run_count`` (the RunLengthEncodedBlock shortcut of the
        reference's aggregation operators) — pure host arithmetic over per-
        batch scalars, no concat, no device dispatch, no expansion."""
        if (len(self.group_keys) or self.step == "FINAL"
                or not self.aggs
                or any(a.distinct for a in self.aggs)
                or not all(a.fn in self._RLE_AGG_FNS for a in self.aggs)):
            return None
        for b in self._batches:
            if b.live is not None and not isinstance(b.live, np.ndarray):
                return None  # counting a device mask would cost a sync
            for a in self.aggs:
                if a.arg < 0:
                    continue
                c = b.columns[a.arg]
                if c.encoding != "RLE":
                    return None
                if c.valid is not None and not isinstance(c.valid, np.ndarray):
                    return None
                if c.dictionary is not None and a.fn == "sum":
                    return None  # dict codes don't sum; min/max do (sorted)
        first = self._batches[0]
        for a in self.aggs:  # min/max on codes needs ONE shared dictionary
            if a.arg < 0 or first.columns[a.arg].dictionary is None:
                continue
            from ..spi.batch import _same_dictionary

            d0 = first.columns[a.arg].dictionary
            if not all(_same_dictionary(b.columns[a.arg].dictionary, d0)
                       for b in self._batches[1:]):
                return None

        def counted(b: ColumnBatch, c: Column) -> int:
            """Rows of this run that are live AND valid."""
            if c.valid is None and b.live is None:
                return len(c)
            m = np.ones(len(c), np.bool_)
            if c.valid is not None:
                m &= np.asarray(c.valid)
            if b.live is not None:
                m &= np.asarray(b.live)
            return int(m.sum())

        out_cols: list[Column] = []
        rows_folded = 0
        for a, t in zip(self.aggs, self.output_types):
            if a.fn == "count_star":
                total = sum(b.live_count for b in self._batches)
                out_cols.append(Column(t, np.array([total], np.int64)))
                continue
            pairs = [(b.columns[a.arg], counted(b, b.columns[a.arg]))
                     for b in self._batches]
            rows_folded += sum(cnt for _, cnt in pairs)
            if a.fn == "count":
                total = sum(cnt for _, cnt in pairs)
                out_cols.append(Column(t, np.array([total], np.int64)))
                continue
            alive = [(c, cnt) for c, cnt in pairs if cnt > 0]
            if not alive:  # sum/min/max over all-NULL input -> NULL
                out_cols.append(Column(t, np.zeros(1, t.storage_dtype),
                                       np.zeros(1, np.bool_),
                                       pairs[0][0].dictionary))
                continue
            dict_ = alive[0][0].dictionary
            if a.fn == "sum":
                dtype = np.dtype(t.storage_dtype)
                if dtype.kind == "f":
                    v = float(sum(float(c.rle_value) * cnt
                                  for c, cnt in alive))
                else:  # exact: python bignum until the final cast
                    v = sum(int(c.rle_value) * cnt for c, cnt in alive)
                out_cols.append(Column(t, np.array([v], dtype)))
            else:
                pick = min if a.fn == "min" else max
                v = pick(c.rle_value for c, _ in alive)
                out_cols.append(Column(
                    t, np.array([v], np.asarray(v).dtype), None, dict_))
        self.encoding_stats.rle_agg_rows += rows_folded
        self.encoding_stats.rle_batches += len(self._batches)
        return ColumnBatch(self.output_names, out_cols)

    def _compute(self) -> ColumnBatch:
        nk = len(self.group_keys)
        if not self.buffered_batches():
            return self._empty_result(nk)
        if encoded_exec():
            fast = self._rle_fast_path()
            if fast is not None:
                return fast
        inp = _maybe_compact_device(_concat_device(self._batches))
        live = inp.live  # None = all rows real
        n = inp.num_rows

        presence = None
        # masked-reduction fast path: small dictionary-code group space and
        # no DISTINCT -> no sort, no gather, no num_groups sync (kernels.
        # small_grouped_aggregate); live folds via the fused gid, so specs
        # skip the fold_live below
        key_cols = [inp.columns[i] for i in self.group_keys]
        space = K.small_codes_group_space(key_cols) if nk else 1
        if nk and space is not None:
            # every key is a small dictionary code: the whole group-by runs
            # in code space (one post-agg gather decodes group keys)
            self.encoding_stats.code_group_batches += 1
        use_masked = (space is not None and space <= K.MASKED_AGG_LIMIT
                      and not any(a.distinct for a in self.aggs)
                      and (nk or live is not None
                           or any(a.arg >= 0 for a in self.aggs)))
        if nk and not use_masked:
            keys = [(c.data, c.valid) for c in key_cols]
            if space is not None:
                # all keys are small dictionary codes: static group space,
                # single-key sort, zero host syncs; empty groups ride out
                # as dead rows in the output's live mask
                perm, gid, num_groups, presence, keys_out = (
                    K.group_ids_codes(key_cols, live))
            else:
                # TRINO_TPU_HASH_IMPL routes between the lexsort path and
                # the Pallas open-addressing path; both honor the same
                # (perm, gid, num_groups) contract, so everything downstream
                # (grouped_reduce, group_keys_out) is implementation-blind
                perm, gid, num_groups = K.group_ids_auto(keys, live)
                if num_groups == 0:  # every row dead (fully filtered input)
                    return self._empty_result(nk)
                keys_out = K.group_keys_out(perm, gid, num_groups, keys)
        elif not nk and not use_masked:
            key_cols, keys_out = [], []
            perm = jnp.arange(n)
            gid = jnp.zeros(n, jnp.int32)
            num_groups = 1

        def fold_live(valid):
            """Dead rows never contribute: fold ``live`` into validity.
            The masked path folds live via the fused group id instead."""
            if use_masked or live is None:
                return valid
            if valid is None:
                return live
            return jnp.asarray(valid) & jnp.asarray(live)

        # kernel specs; avg expands to (sum, count) state pairs, the variance
        # family to (sum, sumsq, count) triples.  FINAL merges partial
        # states: count -> sum of counts, others same fn.
        specs, avg_slots, stat_slots, ld_slots = [], {}, {}, {}

        def _long_dec_col(arg: int):
            if arg < 0:
                return None
            c = inp.columns[arg]
            t = c.type
            if isinstance(t, DecimalType) and t.precision > 18:
                return c
            return None

        for idx, a in enumerate(self.aggs):
            ld_col = (_long_dec_col(a.arg)
                      if a.fn in ("sum", "avg") else None)
            if ld_col is not None:
                # exact wide-decimal SUM/AVG: int64 limb-plane sums on
                # device, bignum recombination per group on host
                # (kernels.decimal_limb_tables; Int128Math.java's role)
                if a.distinct:
                    raise NotImplementedError(
                        "DISTINCT long-decimal aggregate")
                ld_slots[idx] = a.fn
                valid_f = fold_live(ld_col.valid)
                codes_dev = jnp.asarray(ld_col.data)
                for tab in K.decimal_limb_tables(ld_col.dictionary):
                    specs.append(("sum", jnp.asarray(tab)[codes_dev],
                                  valid_f, np.int64, False))
                specs.append(("count", ld_col.data, valid_f, np.int64,
                              False))
                continue
            if self.step == "FINAL":
                c = inp.columns[a.arg]
                data, valid = c.data, fold_live(c.valid)
                if a.fn == "avg":
                    avg_slots[idx] = len(specs)
                    c2 = inp.columns[a.arg + 1]
                    specs.append(("sum", data, valid, np.float64, False))
                    specs.append(("sum", c2.data, fold_live(None), np.int64, False))
                elif a.fn in STAT_AGGS:
                    stat_slots[idx] = len(specs)
                    c2 = inp.columns[a.arg + 1]
                    c3 = inp.columns[a.arg + 2]
                    specs.append(("sum", data, valid, np.float64, False))
                    specs.append(("sum", c2.data, fold_live(c2.valid), np.float64, False))
                    specs.append(("sum", c3.data, fold_live(None), np.int64, False))
                elif a.fn in ("count", "count_star"):
                    specs.append(("sum", data, fold_live(None), np.int64, False))
                else:
                    specs.append((a.fn, data, valid, data.dtype, False))
                continue
            s = self._agg_spec(a, inp, a.type)
            s = (s[0], s[1], fold_live(s[2]), s[3], s[4])
            if s[0] == "avg":
                avg_slots[idx] = len(specs)
                scale = 0
                if a.arg >= 0 and isinstance(inp.columns[a.arg].type, DecimalType):
                    scale = inp.columns[a.arg].type.scale
                # scale-free f64 sum state; the division happens INSIDE the
                # compiled reduce program (pre tag), never as an eager
                # full-size op on the dispatch-latency-bound tunnel path
                specs.append(("sum", s[1], s[2], np.float64, s[4],
                              ("scale", scale)))
                specs.append(("count", s[1], s[2], np.int64, s[4]))
            elif s[0] in STAT_AGGS:
                stat_slots[idx] = len(specs)
                specs.append(("sum", s[1], s[2], np.float64, False))
                specs.append(("sum", s[1], s[2], np.float64, False,
                              ("square",)))
                specs.append(("count", s[1], s[2], np.int64, False))
            else:
                specs.append(s)
        if use_masked:
            reduced, presence, keys_out, num_groups = (
                K.small_grouped_aggregate(key_cols, live, specs))
        else:
            reduced = (K.grouped_reduce(perm, gid, num_groups, specs)
                       if specs else [])

        # finalization (avg division, variance combine, output casts) runs
        # as ONE compiled program over the tiny per-group arrays: zero eager
        # dispatches, and the output columns STAY ON DEVICE so the
        # collective exchange path can feed them straight into all_to_all
        plan: list[tuple] = []
        arrays: list = []
        col_types: list = []
        col_dicts: list = []
        order: list = []  # ("prog",) | ("host", Column) in output position

        def emit(entry, srcs, t, dict_=None):
            plan.append(entry)
            arrays.extend(srcs)
            col_types.append(t)
            col_dicts.append(dict_)
            order.append(("prog", None))

        def emit_host(column):
            order.append(("host", column))

        for (d, v), c in zip(keys_out, key_cols):
            emit(("copy", None, v is not None),
                 [d] + ([v] if v is not None else []), c.type, c.dictionary)
        ri = 0
        ncols = nk
        for idx, a in enumerate(self.aggs):
            t = self.output_types[ncols]
            if idx in ld_slots:
                # exact wide-decimal finalize: pull the tiny per-group limb
                # sums (+count) in ONE round trip, recombine with bignums
                fnname = ld_slots[idx]
                limbs = reduced[ri:ri + 6]
                cnt_res = reduced[ri + 7 - 1]
                ri += 7
                pulled = SG.fetch(
                    [d for d, _ in limbs] + [cnt_res[0]],
                    "agg.decimal-limbs")
                counts = np.asarray(pulled[-1])
                src_scale = 0
                if a.arg >= 0:
                    src_t = inp.columns[a.arg].type
                    if isinstance(src_t, DecimalType):
                        src_scale = src_t.scale
                import decimal as _dec

                values: list = []
                for g in range(num_groups):
                    if int(counts[g]) == 0:
                        values.append(None)
                        continue
                    total = K.combine_limb_sums(
                        [p[g] for p in pulled[:6]])
                    if fnname == "avg":
                        with _dec.localcontext() as ctx:
                            ctx.prec = 80
                            q = (_dec.Decimal(total).scaleb(-src_scale)
                                 / int(counts[g]))
                            values.append(int(q.scaleb(t.scale).quantize(
                                0, rounding=_dec.ROUND_HALF_UP)))
                    else:
                        from ..spi.batch import rescale_scaled_int

                        values.append(rescale_scaled_int(
                            total, src_scale, t.scale))
                from ..spi.batch import encode_sorted_objects

                codes, valid, dict_ = encode_sorted_objects(values, 0)
                emit_host(Column(t, codes, valid, dict_))
                ncols += 1
                continue
            if idx in avg_slots:
                s_data, s_valid = reduced[ri]
                c_data, _ = reduced[ri + 1]
                ri += 2
                if self.step == "PARTIAL":
                    # emit mergeable states: scale-free sum + count
                    emit(("copy", "<f8", s_valid is not None),
                         [s_data] + ([s_valid] if s_valid is not None else []),
                         t)
                    emit(("count", None, False), [c_data],
                         self.output_types[ncols + 1])
                    ncols += 2
                    continue
                emit(("avg_final", np.dtype(t.storage_dtype).str,
                      s_valid is not None),
                     [s_data] + ([s_valid] if s_valid is not None else [])
                     + [c_data], t)
                ncols += 1
                continue
            if idx in stat_slots:
                # variance family: combine (sum, sumsq, count) states
                # (reference: operator/aggregation/VarianceAccumulator)
                s_data, s_valid = reduced[ri]
                q_data, _ = reduced[ri + 1]
                c_data, _ = reduced[ri + 2]
                ri += 3
                if self.step == "PARTIAL":
                    emit(("copy", "<f8", s_valid is not None),
                         [s_data] + ([s_valid] if s_valid is not None else []),
                         t)
                    emit(("copy", "<f8", False), [q_data],
                         self.output_types[ncols + 1])
                    emit(("count", None, False), [c_data],
                         self.output_types[ncols + 2])
                    ncols += 3
                    continue
                emit(("stat_final", a.fn, np.dtype(t.storage_dtype).str,
                      s_valid is not None),
                     [s_data] + ([s_valid] if s_valid is not None else [])
                     + [q_data, c_data], t)
                ncols += 1
                continue
            d, v = reduced[ri]
            ri += 1
            if a.fn not in ("sum", "min", "max", "any_value"):
                v = None  # count never NULL
            dict_ = None
            if self.step != "FINAL" and a.arg >= 0:
                dict_ = inp.columns[a.arg].dictionary
            elif self.step == "FINAL" and a.fn in ("min", "max", "any_value"):
                dict_ = inp.columns[a.arg].dictionary
            emit(("copy", np.dtype(t.storage_dtype).str, v is not None),
                 [d] + ([v] if v is not None else []), t, dict_)
            ncols += 1
        outs = iter(K.finalize_groups(plan, arrays)) if plan else iter([])
        prog_meta = iter(zip(col_types, col_dicts))
        out_cols = []
        for kind, payload in order:
            if kind == "host":
                out_cols.append(payload)
            else:
                d, v = next(outs)
                t, dc = next(prog_meta)
                out_cols.append(Column(t, d, v, dc))
        return ColumnBatch(self.output_names, out_cols, presence)

    def get_output(self) -> Optional[ColumnBatch]:
        if self._flushed:
            return self._flushed.pop(0)
        if self.input_done and self._result is not None and not self._emitted:
            self._emitted = True
            return self._result
        return None

    def is_finished(self) -> bool:
        return (self.input_done and self._emitted
                and not self._flushed) or self._closed


# ---------------------------------------------------------------------------
# joins


class JoinBridge:
    """Build-side handoff between pipelines (the LookupSourceFactory
    equivalent — operator/join/PartitionedLookupSourceFactory.java)."""

    def __init__(self):
        self.table = None  # join_exec.DeviceJoinTable
        self.batch: Optional[ColumnBatch] = None
        self.key_dicts: list[Optional[np.ndarray]] = []
        self._dense: Optional[ColumnBatch] = None

    @property
    def ready(self) -> bool:
        return self.table is not None

    def dense(self) -> ColumnBatch:
        """Host-compacted build batch (cross-join / epilogue paths only)."""
        if self._dense is None:
            self._dense = self.batch.compact()
        return self._dense


def _probe_key_remap(col: Column, build_dict: Optional[np.ndarray]):
    """Host-side remap table translating probe dictionary codes into the
    build side's code space (-1 = value absent, can never match), or None
    when the code spaces already agree.  The table is tiny (dictionary-
    sized); the per-row gather happens inside the probe program on device."""
    pdict = col.dictionary
    if pdict is None and build_dict is None:
        return None
    if build_dict is None or len(build_dict) == 0:
        return np.full(max(len(pdict), 1), -1, np.int32)
    if pdict is None or pdict is build_dict:
        return None
    if pdict.shape == build_dict.shape and (pdict == build_dict).all():
        return None
    pos = np.searchsorted(build_dict, pdict)
    clipped = np.clip(pos, 0, len(build_dict) - 1)
    ok = build_dict[clipped] == pdict
    return np.where(ok, clipped, -1).astype(np.int32)


class JoinBuildSink(BufferedInputMixin, Operator):
    """Accumulates the build side, then builds the sorted-hash join table
    (operator/join/HashBuilderOperator.java:57)."""

    def __init__(self, bridge: JoinBridge, key_channels: Sequence[int],
                 types: Sequence[Type], names: Sequence[str],
                 dynamic_filter_holders=None):
        self.bridge = bridge
        self.key_channels = list(key_channels)
        self.types = list(types)
        self.names = list(names)
        # one holder per key channel (or None) — filled at finish so the
        # probe-side scan can prune (exec/dynamic_filter.py)
        self.dynamic_filter_holders = list(dynamic_filter_holders or [])
        self._batches: list[ColumnBatch] = []

    def add_input(self, batch: ColumnBatch) -> None:
        if batch.num_rows:
            self._batches.append(batch)
            self.account_memory()

    def finish_input(self) -> None:
        from . import join_exec as JX

        super().finish_input()
        if self.buffered_batches():
            # no live-compaction here: the build program sorts dead rows
            # last natively, and a count sync would cost more than the
            # slightly fatter argsort it saves
            batch = _concat_device(self._batches)
        else:
            batch = ColumnBatch(self.names, [
                Column(t, np.empty(0, t.storage_dtype)) for t in self.types])
        live = batch.live
        keys = []
        for ch in self.key_channels:
            c = batch.columns[ch]
            keys.append((c.data, c.valid))
        for k, holder in zip(range(len(self.key_channels)),
                             self.dynamic_filter_holders):
            if holder is not None:
                c = batch.columns[self.key_channels[k]]
                holder.fill_device(c.data, c.valid, live, c.dictionary)
        self.bridge.batch = batch
        self.bridge.key_dicts = [
            batch.columns[ch].dictionary for ch in self.key_channels]
        self.bridge.table = JX.build_table(
            keys, live=live, num_rows=batch.num_rows)
        self.release_memory()

    def is_finished(self) -> bool:
        return self.input_done


def _null_columns(batch: ColumnBatch, n: int) -> list[Column]:
    return [
        Column(c.type, np.zeros(n, c.data.dtype),
               np.zeros(n, bool), c.dictionary)
        for c in batch.columns
    ]


# residual predicates over join candidate pairs: jitted once per
# (expression, types, dictionaries) and evaluated on bucket-padded pair
# batches so repeated probes reuse a handful of compiled programs (the same
# cross-execution caching strategy as FilterProjectOperator._PROGRAM_CACHE)
_RESIDUAL_CACHE: dict = {}
_RESIDUAL_LOCK = threading.Lock()


def _residual_program(expr: RowExpression, types, dicts):
    key = (expr, tuple(types),
           tuple(id(d) if d is not None else None for d in dicts))
    with _RESIDUAL_LOCK:
        hit = _RESIDUAL_CACHE.get(key)
        if hit is not None:
            return hit[0]
    ce = compile_expression(expr, list(types), list(dicts))

    def run(cols):
        data, valid = ce(cols)
        return data if valid is None else (data & valid)

    prog = jax.jit(run)
    with _RESIDUAL_LOCK:
        _RESIDUAL_CACHE.setdefault(key, (prog, list(dicts)))
        if len(_RESIDUAL_CACHE) > 1024:
            _RESIDUAL_CACHE.pop(next(iter(_RESIDUAL_CACHE)))
    return prog


def _pad_indices(idx: np.ndarray) -> tuple[np.ndarray, int]:
    """Pad an index vector to its power-of-two bucket (clamped repeats of
    slot 0 keep gathers in-range; callers mask the tail with ``live``)."""
    n = len(idx)
    cap = K.bucket(n)
    if cap == n:
        return idx, n
    return np.concatenate([idx, np.zeros(cap - n, idx.dtype)]), n


def _nested_loop_pairs(probe: ColumnBatch, build: ColumnBatch,
                       residual: Optional[RowExpression]):
    """Host nested-loop pair expansion shared by the cross join and the
    keyless semi-join (operator/join/NestedLoopJoinOperator.java:45): the
    full (probe x build) product, filtered by the jitted residual program.
    Returns post-residual (pi, bi) index arrays."""
    nb = build.num_rows
    pi = np.repeat(np.arange(probe.num_rows, dtype=np.int64), nb)
    bi = np.tile(np.arange(nb, dtype=np.int64), probe.num_rows)
    if residual is None or not len(pi):
        return pi, bi
    pidx, n = _pad_indices(pi)
    bidx, _ = _pad_indices(bi)
    cols = ([c.take(pidx) for c in probe.columns]
            + [c.take(bidx) for c in build.columns])
    pair = ColumnBatch([f"c{i}" for i in range(len(cols))], cols)
    prog = _residual_program(
        residual, [c.type for c in pair.columns],
        [c.dictionary for c in pair.columns])
    mask = np.asarray(
        SG.fetch(prog(_to_cols(pair)), "join.nested-loop-residual"))[:n]
    return pi[mask], bi[mask]


class LookupJoinOperator(Operator):
    """Probe side of the equi-join (operator/join/LookupJoinOperator.java:37).
    Streams probe batches against the finished build table.  The whole probe
    runs on device (exec/join_exec.py): candidate ranges, expansion, exact
    verification, residual, and output gathers are jitted programs; the only
    blocking host interaction per batch is the one scalar candidate-count
    sync that picks the expansion bucket.  RIGHT/FULL track matched build
    positions across all probe batches and emit the unmatched build rows
    null-extended after input finishes (the OUTER lookup-source variants of
    the reference)."""

    def __init__(self, bridge: JoinBridge, left_keys: Sequence[int],
                 join_type: str, residual: Optional[RowExpression],
                 output_names: Sequence[str], output_types: Sequence[Type]):
        self.bridge = bridge
        self.left_keys = list(left_keys)
        self.join_type = join_type
        self.residual = residual
        self.output_names = list(output_names)
        self.output_types = list(output_types)
        from collections import deque

        from . import join_exec as JX

        self._pending: "deque[ColumnBatch]" = deque()
        self._build_matched = None  # device bool per build slot (RIGHT/FULL)
        self._emitted_unmatched = False
        # probe-side dictionaries observed, for null-extended unmatched rows
        self._probe_dicts: Optional[list] = None
        # sync-free expand state: capacity planners fed by async-landed
        # totals, and the deferred-commit queue for estimated-cap batches
        # whose overflow flag is still in flight (exec/join_exec.py)
        # keyed planners: the same join shape re-planned in a later
        # execution starts from the prior run's observed totals
        ident = (join_type, tuple(self.left_keys),
                 tuple(self.output_names), residual is not None)
        self._planner = JX.ExpandPlanner(key=("pairs",) + ident)
        self._uplanner = JX.ExpandPlanner(key=("unique",) + ident)
        self._inflight = JX.OverflowQueue()
        self.pending_errors: list = []  # deferred cardinality violations
        self.encoding_stats = EncodingStats()

    def needs_input(self) -> bool:
        return self.bridge.ready and not self._pending and super().needs_input()

    def _add_cross_input(self, probe: ColumnBatch) -> None:
        """Nested-loop fallback (operator/join/NestedLoopJoinOperator.java:45)
        — host-side; inherently quadratic and only planned for tiny inputs."""
        probe = probe.compact()
        build = self.bridge.dense()
        nb = build.num_rows
        self._dense_build = build  # epilogue indexes match this batch
        if self.join_type == "SINGLE" and nb > 1 and probe.num_rows:
            raise TrinoError(SUBQUERY_MULTIPLE_ROWS,
                             "scalar subquery returned multiple rows")
        pi, bi = _nested_loop_pairs(probe, build, self.residual)
        if self.join_type in ("RIGHT", "FULL"):
            if self._build_matched is None:
                self._build_matched = np.zeros(nb, bool)
            if len(bi):
                m = np.asarray(self._build_matched)
                m[bi] = True
                self._build_matched = m
            self._probe_dicts = [c.dictionary for c in probe.columns]
        if self.join_type in ("LEFT", "SINGLE", "FULL"):
            matched = np.zeros(probe.num_rows, bool)
            matched[pi] = True
            un = np.nonzero(~matched)[0]
            if len(un):
                left_cols = [c.take(un) for c in probe.columns]
                right_cols = _null_columns(build, len(un))
                self._pending.append(ColumnBatch(
                    self.output_names, left_cols + right_cols))
        if len(pi):
            cols = ([c.take(pi) for c in probe.columns]
                    + [c.take(bi) for c in build.columns])
            self._pending.append(ColumnBatch(self.output_names, cols))

    def add_input(self, probe: ColumnBatch) -> None:
        from . import join_exec as JX

        if not self.left_keys:  # cross join (nested-loop fallback)
            self._add_cross_input(probe)
            return
        build = self.bridge.batch
        table = self.bridge.table
        keys = [(JX.key_input(probe.columns[ch]), probe.columns[ch].valid)
                for ch in self.left_keys]
        remaps = [
            _probe_key_remap(probe.columns[ch], self.bridge.key_dicts[k])
            for k, ch in enumerate(self.left_keys)
        ]
        if any(r is not None for r in remaps):
            # dictionary keys probe as remapped int32 CODES, never values
            self.encoding_stats.code_join_batches += 1
        if table.num_rows:
            if self.join_type in ("INNER", "RIGHT"):
                # speculative FK->PK probe: ranges+verify first, ONE combined
                # (count, max-run) sync, then a width-adaptive gather; falls
                # through to the pair path only when the build proved
                # non-unique (exec/join_exec.py r5 design notes)
                if self._add_inner_unique(probe, table, build, keys, remaps):
                    return
            elif table.unique:
                # LEFT/SINGLE/FULL keep every probe row: the wide one-program
                # path with zero per-batch syncs
                self._add_unique_input(probe, table, build, keys, remaps)
                return
        self._add_pairs(probe, table, build, keys, remaps)

    def _null_extended(self, probe: ColumnBatch, build: ColumnBatch,
                       un_live) -> ColumnBatch:
        """Unmatched probe rows ride the ORIGINAL probe batch shape with a
        live mask (no gather, no compaction): probe columns pass through,
        build columns are all-NULL."""
        n = probe.num_rows
        right_cols = [
            Column(c.type, jnp.zeros(n, c.type.storage_dtype),
                   jnp.zeros(n, jnp.bool_), c.dictionary)
            for c in build.columns
        ]
        return ColumnBatch(
            self.output_names, list(probe.columns) + right_cols, un_live)

    def _add_pairs(self, probe: ColumnBatch, table, build,
                   keys, remaps) -> None:
        """General (non-unique build) probe: candidate ranges + padded
        expand.  Sync-free mode picks the expand bucket from build-side
        statistics (ExpandPlanner) so the steady state never blocks on the
        candidate total; TRINO_TPU_SYNC_FREE=0 keeps the legacy
        one-total-sync-per-batch behavior."""
        from . import join_exec as JX

        need_matched = self.join_type in ("LEFT", "SINGLE", "FULL")
        if self.join_type in ("RIGHT", "FULL"):
            self._probe_dicts = [c.dictionary for c in probe.columns]
        if probe.num_rows == 0:
            return
        if table.num_rows == 0:  # empty build: no pairs, all probes unmatched
            if need_matched:
                self._pending.append(
                    self._null_extended(probe, build, probe.live))
            return
        probe_cols = [(c.data, c.valid) for c in probe.columns]
        build_cols = [(c.data, c.valid) for c in build.columns]
        pair_types = ([c.type for c in probe.columns]
                      + [c.type for c in build.columns])
        pair_dicts = ([c.dictionary for c in probe.columns]
                      + [c.dictionary for c in build.columns])
        sf = _sync_free()

        def commit(res) -> None:
            pairs, ok, matched, maxc, build_id, _overflow = res
            if self.join_type == "SINGLE" and sf:
                # scalar subquery: >1 match per probe row is a cardinality
                # violation (EnforceSingleRowNode semantics).  The check
                # stays a device scalar on the deferred error channel —
                # raised by check_error_scalars at pipeline end, costing
                # zero extra syncs here (ops/expr.py)
                from ..ops.expr import SUBQUERY_MULTIPLE_ROWS

                self.pending_errors.append(jnp.where(
                    jnp.asarray(maxc) > 1, SUBQUERY_MULTIPLE_ROWS, 0))
            if self.join_type in ("RIGHT", "FULL"):
                if self._build_matched is None:
                    self._build_matched = jnp.zeros(build.num_rows, jnp.bool_)
                self._build_matched = jnp.asarray(
                    self._build_matched).at[build_id].max(ok)
            out_cols = [Column(t, d, v, dc) for (d, v), t, dc in
                        zip(pairs, pair_types, pair_dicts)]
            self._pending.append(
                ColumnBatch(self.output_names, out_cols, ok))
            if need_matched:
                un_live = ~matched if probe.live is None else (
                    jnp.asarray(probe.live) & ~matched)
                self._pending.append(
                    self._null_extended(probe, build, un_live))

        if not sf:
            # legacy: ONE blocking candidate-total sync picks the bucket
            lo, counts, total = JX.probe_ranges(
                table, keys, remaps, probe.live)
            if not total:
                if need_matched:  # nothing matched: all live rows pass
                    self._pending.append(
                        self._null_extended(probe, build, probe.live))
                return
            res = JX.run_pairs(
                table, lo, counts, total, keys, remaps, probe_cols,
                build_cols, pair_types, pair_dicts, self.residual,
                need_matched)
            if self.join_type == "SINGLE" and int(
                    SG.fetch(res[3], "join.single-maxc")) > 1:
                raise TrinoError(SUBQUERY_MULTIPLE_ROWS,
                                 "scalar subquery returned multiple rows")
            commit(res)
            return

        with SG.hot_region():
            lo, counts, total_a = JX.probe_ranges_device(
                table, keys, remaps, probe.live)
            cap, provable = self._planner.plan(probe.num_rows, table.max_run)
            self._planner.observe_async(total_a)
            res = JX.run_pairs(
                table, lo, counts, total_a, keys, remaps, probe_cols,
                build_cols, pair_types, pair_dicts, self.residual,
                need_matched, cap=cap, donate=provable)
            if provable:  # cap >= any possible total: no overflow, no retry
                commit(res)
                return

            def retry():
                # rare: the estimated bucket truncated candidates — re-run
                # at the exact total (landed long ago by drain time)
                total_h = max(int(total_a.get()), 1)
                self._planner.observe(total_h)
                return JX.run_pairs(
                    table, lo, counts, total_h, keys, remaps, probe_cols,
                    build_cols, pair_types, pair_dicts, self.residual,
                    need_matched)

            self._inflight.push(
                SG.async_scalar(res[5], "join.expand-overflow"),
                res, retry, commit)
            self._inflight.drain()

    def _add_inner_unique(self, probe: ColumnBatch, table, build,
                          keys, remaps) -> bool:
        """INNER/RIGHT probe against a (speculatively) unique build.
        Returns False when the build turned out non-unique — the caller
        falls back to the general pair path."""
        from . import join_exec as JX

        sf = _sync_free()
        if sf:
            # uniqueness comes from the per-BUILD scalar fetch (amortized
            # over every probe batch); ranges + count stay on device
            if not table.unique:
                return False
            if probe.num_rows == 0:
                return True
            ok_live, bid, cnt_a = JX.run_unique_ranges_device(
                table, keys, remaps, probe.live)
            cnt = None
        else:
            ok_live, bid, cnt, mr = JX.run_unique_ranges(
                table, keys, remaps, probe.live)
            if mr > 1:
                return False
        if self.join_type == "RIGHT":
            self._probe_dicts = [c.dictionary for c in probe.columns]
        if cnt == 0:  # legacy only (sync-free never knows the exact count)
            return True  # nothing matched; RIGHT epilogue emits build rows
        probe_cols = [(c.data, c.valid) for c in probe.columns]
        build_cols = [(c.data, c.valid) for c in build.columns]
        pair_types = ([c.type for c in probe.columns]
                      + [c.type for c in build.columns])
        pair_dicts = ([c.dictionary for c in probe.columns]
                      + [c.dictionary for c in build.columns])
        need_bm = self.join_type == "RIGHT"

        def commit(res) -> None:
            p_out, b_out, live, bm, _overflow = res
            if need_bm and bm is not None:
                if self._build_matched is None:
                    self._build_matched = bm
                else:
                    self._build_matched = jnp.asarray(
                        self._build_matched) | bm
            if p_out is None:  # wide: probe columns pass through untouched
                left_cols = list(probe.columns)
            else:
                left_cols = [Column(c.type, d, v, c.dictionary)
                             for c, (d, v) in zip(probe.columns, p_out)]
            right_cols = [Column(c.type, d, v, c.dictionary)
                          for c, (d, v) in zip(build.columns, b_out)]
            self._pending.append(ColumnBatch(
                self.output_names, left_cols + right_cols, live))

        if not sf:
            cap = JX.plan_unique_cap(probe.num_rows, cnt)
            commit(JX.run_unique_gather(
                table, ok_live, bid, cap, probe_cols, build_cols,
                pair_types, pair_dicts, self.residual, need_bm))
            return True

        with SG.hot_region():
            # compact-vs-wide from the previous batches' async-landed match
            # counts; the compact path's overflow flag guards the estimate
            est = self._uplanner.recent_max()
            cap = JX.plan_unique_cap(
                probe.num_rows,
                None if est is None else est * JX.EST_HEADROOM)
            self._uplanner.observe_async(cnt_a)
            res = JX.run_unique_gather(
                table, ok_live, bid, cap, probe_cols, build_cols,
                pair_types, pair_dicts, self.residual, need_bm)
            if cap is None:  # wide path cannot overflow
                commit(res)
                return True

            def retry():
                # compact bucket overflowed: re-run wide (provably safe)
                return JX.run_unique_gather(
                    table, ok_live, bid, None, probe_cols, build_cols,
                    pair_types, pair_dicts, self.residual, need_bm)

            self._inflight.push(
                SG.async_scalar(res[4], "join.unique-overflow"),
                res, retry, commit)
            self._inflight.drain()
        return True

    def _add_unique_input(self, probe: ColumnBatch, table, build,
                          keys, remaps) -> None:
        """Unique-build probe: ONE program, probe columns pass through, the
        output rides the probe batch's shape with the match mask as live.
        Covers every join type: LEFT/SINGLE/FULL keep unmatched probe rows
        as NULL-extended lanes of the same batch (no second batch), SINGLE
        can never violate cardinality (<=1 match by construction)."""
        from . import join_exec as JX

        need_res_cols = self.residual is not None
        probe_cols = ([(c.data, c.valid) for c in probe.columns]
                      if need_res_cols else [])
        build_cols = [(c.data, c.valid) for c in build.columns]
        if need_res_cols:
            pair_types = ([c.type for c in probe.columns]
                          + [c.type for c in build.columns])
            pair_dicts = ([c.dictionary for c in probe.columns]
                          + [c.dictionary for c in build.columns])
        else:
            pair_types, pair_dicts = [], []
        need_bm = self.join_type in ("RIGHT", "FULL")
        with SG.hot_region():
            bgather, ok_live, build_matched, _ = JX.run_unique(
                table, keys, remaps, probe_cols, build_cols,
                pair_types, pair_dicts, self.residual, need_bm,
                live=probe.live)
        if need_bm:
            self._probe_dicts = [c.dictionary for c in probe.columns]
            if self._build_matched is None:
                self._build_matched = build_matched
            else:
                self._build_matched = (
                    jnp.asarray(self._build_matched) | build_matched)
        right_cols = [Column(c.type, d, v, c.dictionary)
                      for c, (d, v) in zip(build.columns, bgather)]
        if self.join_type in ("INNER", "RIGHT"):
            out_live = ok_live
        else:  # LEFT / SINGLE / FULL: unmatched probe rows stay live,
            # their build columns already read NULL (valid folds the mask)
            out_live = probe.live
        self._pending.append(ColumnBatch(
            self.output_names, list(probe.columns) + right_cols, out_live))

    _dense_build: Optional[ColumnBatch] = None  # set by the cross path

    def _unmatched_build_batch(self) -> Optional[ColumnBatch]:
        """RIGHT/FULL epilogue: build rows no probe row matched, with NULL
        probe-side columns (runs once; host-side)."""
        build = (self._dense_build if self._dense_build is not None
                 else self.bridge.batch)
        if build is None or build.num_rows == 0:
            return None
        matched = (np.asarray(self._build_matched)
                   if self._build_matched is not None
                   else np.zeros(build.num_rows, bool))
        alive = (np.ones(build.num_rows, bool) if build.live is None
                 else np.asarray(build.live))
        un = np.nonzero(alive & ~matched)[0]
        if not len(un):
            return None
        lw = len(self.output_types) - build.num_columns
        n = len(un)
        left_cols = []
        for i, t in enumerate(self.output_types[:lw]):
            d = (self._probe_dicts[i]
                 if self._probe_dicts is not None else None)
            left_cols.append(Column(t, np.zeros(n, t.storage_dtype),
                                    np.zeros(n, bool), d))
        right_cols = [c.take(un) for c in build.columns]
        return ColumnBatch(self.output_names, left_cols + right_cols)

    def get_output(self) -> Optional[ColumnBatch]:
        if len(self._inflight):
            # commit landed estimated-cap batches; at input end the tail
            # entries are waited on (the only blocking poll of the query)
            self._inflight.drain(block=self.input_done)
        if self._pending:
            return self._pending.popleft()
        if (self.input_done and not self._closed
                and self.join_type in ("RIGHT", "FULL")
                and not self._emitted_unmatched):
            self._emitted_unmatched = True
            return self._unmatched_build_batch()
        return None

    def is_finished(self) -> bool:
        if self._closed:
            return True
        done = (self.input_done and not self._pending
                and not len(self._inflight))
        if self.join_type in ("RIGHT", "FULL"):
            return done and self._emitted_unmatched
        return done


class SemiJoinOperator(Operator):
    """Mark join for IN / EXISTS (operator/HashSemiJoinOperator.java:47):
    output = source channels + a BOOLEAN match column.  Three-valued
    semantics for null-aware IN: no-match becomes NULL (not FALSE) when the
    probe key is NULL or the build side contains a NULL key, so a downstream
    ``$not`` yields NULL and the row is filtered — exactly NOT IN."""

    def __init__(self, bridge: JoinBridge, source_keys: Sequence[int],
                 null_aware: bool, residual: Optional[RowExpression],
                 output_names: Sequence[str], output_types: Sequence[Type]):
        self.bridge = bridge
        self.source_keys = list(source_keys)
        self.null_aware = null_aware
        self.residual = residual
        self.output_names = list(output_names)
        self.output_types = list(output_types)
        from collections import deque

        from . import join_exec as JX

        self._pending: "deque[ColumnBatch]" = deque()
        self._planner = JX.ExpandPlanner(key=(
            "semi", tuple(self.source_keys), null_aware,
            tuple(self.output_names), residual is not None))
        self._inflight = JX.OverflowQueue()

    def needs_input(self) -> bool:
        return self.bridge.ready and not self._pending and super().needs_input()

    def _add_keyless_input(self, batch: ColumnBatch) -> None:
        """EXISTS with only non-equi residuals decorrelates to a keyless
        semi-join: every probe row pairs with every build row and the
        residual alone decides the mark (host nested-loop fallback)."""
        batch = batch.compact()
        build = self.bridge.dense()
        pi, _ = _nested_loop_pairs(batch, build, self.residual)
        matched = np.zeros(batch.num_rows, bool)
        matched[pi] = True
        mark = Column(BOOLEAN, matched, None)
        self._pending.append(ColumnBatch(
            self.output_names, list(batch.columns) + [mark], batch.live))

    def add_input(self, batch: ColumnBatch) -> None:
        from . import join_exec as JX

        if not self.source_keys:
            self._add_keyless_input(batch)
            return
        table = self.bridge.table
        build = self.bridge.batch
        if table.num_rows == 0:
            # IN over the empty set is FALSE (never UNKNOWN)
            mark = Column(BOOLEAN, np.zeros(batch.num_rows, bool), None)
            self._pending.append(ColumnBatch(
                self.output_names, list(batch.columns) + [mark], batch.live))
            return
        if batch.num_rows == 0:
            mark = Column(BOOLEAN, np.zeros(0, bool), None)
            self._pending.append(ColumnBatch(
                self.output_names, list(batch.columns) + [mark], batch.live))
            return
        keys = []
        remaps = []
        for k, ch in enumerate(self.source_keys):
            c = batch.columns[ch]
            bdict = (self.bridge.key_dicts[k]
                     if k < len(self.bridge.key_dicts) else None)
            keys.append((JX.key_input(c), c.valid))
            remaps.append(_probe_key_remap(c, bdict))
        # IN over the empty set is FALSE (never UNKNOWN) even for NULL probes
        semi = (self.null_aware, table.has_null_key, table.live_rows > 0)
        if table.unique:
            if self.residual is not None:
                probe_cols = [(c.data, c.valid) for c in batch.columns]
                build_cols = [(c.data, c.valid) for c in build.columns]
                pair_types = ([c.type for c in batch.columns]
                              + [c.type for c in build.columns])
                pair_dicts = ([c.dictionary for c in batch.columns]
                              + [c.dictionary for c in build.columns])
            else:
                probe_cols, build_cols, pair_types, pair_dicts = [], [], [], []
            with SG.hot_region():
                _, _, _, mark_out = JX.run_unique(
                    table, keys, remaps, probe_cols, build_cols,
                    pair_types, pair_dicts, self.residual, False, semi=semi,
                    live=batch.live)
            mark_data, mark_valid = mark_out
            mark = Column(BOOLEAN, mark_data, mark_valid)
            self._pending.append(ColumnBatch(
                self.output_names, list(batch.columns) + [mark], batch.live))
            return
        if self.residual is not None:
            probe_cols = [(c.data, c.valid) for c in batch.columns]
            build_cols = [(c.data, c.valid) for c in build.columns]
            pair_types = ([c.type for c in batch.columns]
                          + [c.type for c in build.columns])
            pair_dicts = ([c.dictionary for c in batch.columns]
                          + [c.dictionary for c in build.columns])
        else:
            probe_cols, build_cols, pair_types, pair_dicts = [], [], [], []

        def commit(res) -> None:
            mark_data, mark_valid = res[4]
            mark = Column(BOOLEAN, mark_data, mark_valid)
            self._pending.append(ColumnBatch(
                self.output_names, list(batch.columns) + [mark], batch.live))

        if not _sync_free():
            lo, counts, total = JX.probe_ranges(
                table, keys, remaps, batch.live)
            commit(JX.run_pairs(
                table, lo, counts, total, keys, remaps, probe_cols,
                build_cols, pair_types, pair_dicts, self.residual, False,
                semi=semi))
            return

        with SG.hot_region():
            lo, counts, total_a = JX.probe_ranges_device(
                table, keys, remaps, batch.live)
            cap, provable = self._planner.plan(batch.num_rows, table.max_run)
            self._planner.observe_async(total_a)
            res = JX.run_pairs(
                table, lo, counts, total_a, keys, remaps, probe_cols,
                build_cols, pair_types, pair_dicts, self.residual, False,
                semi=semi, cap=cap, donate=provable)
            if provable:
                commit(res)
                return

            def retry():
                total_h = max(int(total_a.get()), 1)
                self._planner.observe(total_h)
                return JX.run_pairs(
                    table, lo, counts, total_h, keys, remaps, probe_cols,
                    build_cols, pair_types, pair_dicts, self.residual,
                    False, semi=semi)

            self._inflight.push(
                SG.async_scalar(res[5], "join.expand-overflow"),
                res, retry, commit)
            self._inflight.drain()

    def get_output(self) -> Optional[ColumnBatch]:
        if len(self._inflight):
            self._inflight.drain(block=self.input_done)
        return self._pending.popleft() if self._pending else None

    def is_finished(self) -> bool:
        return (self.input_done and not self._pending
                and not len(self._inflight))


# ---------------------------------------------------------------------------
# window


class WindowOperator(BufferedInputMixin, Operator):
    """Window-function evaluation (operator/WindowOperator.java:69): blocking
    — accumulate, then one jitted program per (spec, shape bucket) computes
    every function and scatters results back to input order (see
    exec/window_kernels.py)."""

    def __init__(self, partition_keys: Sequence[int],
                 order_keys: Sequence[SortKey],
                 functions: Sequence[WindowFunc],
                 output_names: Sequence[str], output_types: Sequence[Type]):
        self.partition_keys = list(partition_keys)
        self.order_keys = list(order_keys)
        self.functions = list(functions)
        self.output_names = list(output_names)
        self.output_types = list(output_types)
        self._batches: list[ColumnBatch] = []
        self._result: Optional[ColumnBatch] = None
        self._emitted = False

    def add_input(self, batch: ColumnBatch) -> None:
        if batch.num_rows:
            self._batches.append(batch)
            self.account_memory()

    def finish_input(self) -> None:
        super().finish_input()
        if not self.buffered_batches():
            self._result = ColumnBatch(
                self.output_names,
                [Column(t, np.empty(0, t.storage_dtype))
                 for t in self.output_types])
            return
        inp = ColumnBatch.concat(self._batches)  # compacts + unifies dicts
        pkeys = [(inp.columns[c].data, inp.columns[c].valid)
                 for c in self.partition_keys]
        okeys = [(inp.columns[k.channel].data, inp.columns[k.channel].valid,
                  k.ascending, k.nulls_first) for k in self.order_keys]
        specs = []
        fn_dicts = []
        for f in self.functions:
            acols = [inp.columns[c] for c in f.args]
            if len(acols) > 1 and acols[0].type.is_dictionary_encoded:
                # lag/lead default drawn from a different dictionary column
                acols = unify_dictionaries(acols)
            args = [(c.data, c.valid) for c in acols]
            fn_dicts.append(acols[0].dictionary if acols else None)
            specs.append({
                "fn": f.fn, "args": args, "offset": f.offset,
                "frame": f.frame, "dtype": f.type.storage_dtype,
            })
        results = WK.compute_windows(pkeys, okeys, specs, inp.num_rows)
        out_cols = list(inp.columns)
        for f, (data, valid), fdict in zip(self.functions, results, fn_dicts):
            dict_ = None
            if f.args and f.fn not in ("count", "sum", "avg"):
                dict_ = fdict
            if f.fn in ("row_number", "rank", "dense_rank", "percent_rank",
                        "cume_dist", "ntile", "count", "count_star"):
                valid = None  # never NULL
            out_cols.append(Column(f.type, data, valid, dict_))
        self._result = ColumnBatch(self.output_names, out_cols)
        self.release_memory()

    def get_output(self) -> Optional[ColumnBatch]:
        if self._result is not None and not self._emitted:
            self._emitted = True
            return self._result
        return None

    def is_finished(self) -> bool:
        return (self.input_done and self._emitted) or self._closed


# ---------------------------------------------------------------------------
# sort / topn / limit / distinct


def _sort_key_tuples(batch: ColumnBatch, keys: Sequence[SortKey]):
    out = []
    for k in keys:
        c = batch.columns[k.channel]
        out.append((np.asarray(c.data),
                    None if c.valid is None else np.asarray(c.valid),
                    k.ascending, k.nulls_first))
    return out


def _any_device(batches: Sequence[ColumnBatch]) -> bool:
    for b in batches:
        if b.live is not None and not isinstance(b.live, np.ndarray):
            return True
        for c in b.columns:
            if not isinstance(c.data, np.ndarray):
                return True
    return False


class SortOperator(BufferedInputMixin, Operator):
    """Full sort (operator/OrderByOperator.java:44).  Device-resident input
    sorts on chip as ONE jitted program (lexsort + payload gather, dead rows
    last) with zero host syncs; small host-resident input keeps the numpy
    path — shipping tiny post-aggregation sorts through a tunneled device
    costs ~1000x the sort itself."""

    limit: Optional[int] = None  # TopN sets this

    def __init__(self, keys: Sequence[SortKey]):
        self.keys = list(keys)
        self._batches: list[ColumnBatch] = []
        self._result = None
        self._emitted = False

    def add_input(self, batch: ColumnBatch) -> None:
        if batch.num_rows:
            self._batches.append(batch)
            self.account_memory()

    def _sorted_batch(self, batches: Sequence[ColumnBatch],
                      out_n: Optional[int]) -> ColumnBatch:
        if _any_device(batches):
            inp = _maybe_compact_device(_concat_device(batches))
            keys = [(inp.columns[k.channel].data, inp.columns[k.channel].valid,
                     k.ascending, k.nulls_first) for k in self.keys]
            cols = [(c.data, c.valid) for c in inp.columns]
            n = inp.num_rows
            cap = None if out_n is None else min(out_n, n)
            outs, live = K.device_sort(keys, cols, inp.live, cap)
            out_cols = [Column(c.type, d, v, c.dictionary)
                        for (d, v), c in zip(outs, inp.columns)]
            return ColumnBatch(inp.names, out_cols, live)
        inp = ColumnBatch.concat(batches)
        perm = K.sort_perm(_sort_key_tuples(inp, self.keys))
        if out_n is not None:
            perm = np.asarray(perm)[:out_n]
        return inp.take(perm)

    def finish_input(self) -> None:
        super().finish_input()
        if not self.buffered_batches():
            self._emitted = True
            return
        self._result = self._sorted_batch(self._batches, self.limit)
        self.release_memory()

    def get_output(self):
        if self._result is not None and not self._emitted:
            self._emitted = True
            return self._result
        return None

    def is_finished(self) -> bool:
        return self.input_done and self._emitted


class TopNOperator(SortOperator):
    """Streaming top-N (operator/TopNOperator.java:34): when the buffer
    outgrows a multiple of N, it is compacted to the current best N rows, so
    state stays O(N + batch) instead of O(input)."""

    def __init__(self, count: int, keys: Sequence[SortKey]):
        super().__init__(keys)
        self.count = count
        self.limit = count
        self._buffered_rows = 0
        self._shrink_at = max(4 * count, 1 << 16)

    def add_input(self, batch: ColumnBatch) -> None:
        if not batch.num_rows:
            return
        self._batches.append(batch)
        self._buffered_rows += batch.num_rows
        if self._buffered_rows > self._shrink_at:
            self._shrink()
        self.account_memory()

    def _shrink(self) -> None:
        best = self._sorted_batch(self.buffered_batches(), self.count)
        self._batches = [best]
        self._buffered_rows = best.num_rows


class GroupIdOperator(Operator):
    """Grouping-sets row expansion (reference: operator/GroupIdOperator.java:32):
    each input batch yields one output batch per grouping set — grouping
    columns absent from the set become all-NULL copies, aggregation-argument
    channels pass through untouched, and a constant $groupid column tags the
    set.  Masking instead of replicating row-by-row keeps every emitted batch
    the same fixed shape as its input (XLA-friendly; no dynamic fan-out)."""

    def __init__(self, key_channels, passthrough, sets, output_names,
                 output_types):
        self.key_channels = list(key_channels)
        self.passthrough = list(passthrough)
        self.sets = [tuple(s) for s in sets]
        self.output_names = list(output_names)
        self.gid_type = output_types[-1]
        self._queue: list[ColumnBatch] = []

    def needs_input(self) -> bool:
        return not self._queue and super().needs_input()

    def add_input(self, batch: ColumnBatch) -> None:
        n = batch.num_rows
        for gid, live_keys in enumerate(self.sets):
            cols = []
            for idx, ch in enumerate(self.key_channels):
                c = batch.columns[ch]
                if idx in live_keys:
                    cols.append(c)
                else:
                    # all-NULL copy; keep the array backend (host vs device)
                    if isinstance(c.data, np.ndarray):
                        invalid = np.zeros(n, dtype=np.bool_)
                    else:
                        import jax.numpy as jnp

                        invalid = jnp.zeros(n, dtype=jnp.bool_)
                    cols.append(Column(c.type, c.data, invalid, c.dictionary))
            for ch in self.passthrough:
                cols.append(batch.columns[ch])
            cols.append(Column(self.gid_type,
                               np.full(n, gid, dtype=np.int64)))
            self._queue.append(ColumnBatch(self.output_names, cols, batch.live))

    def get_output(self) -> Optional[ColumnBatch]:
        if self._queue:
            return self._queue.pop(0)
        return None

    def is_finished(self) -> bool:
        return self.input_done and not self._queue


class UnnestOperator(Operator):
    """Array row expansion (reference: operator/unnest/UnnestOperator.java:42).
    Host-side by design: fan-out is inherently dynamic-shape, and array
    values live in the host dictionary (spi/types.ArrayType).  Multiple
    arrays zip-pad to the longest per row (Trino semantics); rows where
    every array is empty/NULL are dropped (CROSS JOIN UNNEST)."""

    def __init__(self, replicate, unnest_channels, ordinality, output_names,
                 output_types):
        self.replicate = list(replicate)
        self.unnest_channels = list(unnest_channels)
        self.ordinality = ordinality
        self.output_names = list(output_names)
        self.output_types = list(output_types)
        self._pending: Optional[ColumnBatch] = None

    def needs_input(self) -> bool:
        return self._pending is None and super().needs_input()

    def add_input(self, batch: ColumnBatch) -> None:
        batch = batch.compact()
        n = batch.num_rows
        if n == 0:
            return
        per_col: list[list[tuple]] = []
        for ch in self.unnest_channels:
            c = batch.columns[ch]
            codes = np.asarray(c.data)
            valid = c.valid_mask()
            d = c.dictionary
            per_col.append([
                tuple(d[codes[i]]) if valid[i] else () for i in range(n)])
        lengths = np.array(
            [max(len(a[i]) for a in per_col) for i in range(n)],
            dtype=np.int64)
        idx = np.repeat(np.arange(n), lengths)
        if not len(idx):
            return
        pos = np.concatenate([np.arange(l) for l in lengths if l])
        cols = [batch.columns[ch].take(idx) for ch in self.replicate]
        k = len(self.replicate)
        for j in range(len(per_col)):
            et = self.output_types[k + j]
            vals = [
                per_col[j][r][p] if p < len(per_col[j][r]) else None
                for r, p in zip(idx, pos)]
            cols.append(Column.from_values(et, vals))
        if self.ordinality:
            cols.append(Column(self.output_types[-1],
                               (pos + 1).astype(np.int64)))
        self._pending = ColumnBatch(self.output_names, cols)

    def get_output(self) -> Optional[ColumnBatch]:
        b, self._pending = self._pending, None
        return b

    def is_finished(self) -> bool:
        return self.input_done and self._pending is None


class ReplicateOperator(Operator):
    """Emit each row N times, N from a count channel (the row-expansion leg
    of INTERSECT/EXCEPT ALL — see planner Replicate node)."""

    def __init__(self, count_channel: int):
        self.count_channel = count_channel
        self._pending: Optional[ColumnBatch] = None

    def needs_input(self) -> bool:
        return self._pending is None and super().needs_input()

    def add_input(self, batch: ColumnBatch) -> None:
        batch = batch.compact()
        counts = np.asarray(batch.columns[self.count_channel].data)
        counts = np.clip(counts, 0, None)
        idx = np.repeat(np.arange(batch.num_rows), counts)
        if len(idx):
            self._pending = batch.take(idx)

    def get_output(self) -> Optional[ColumnBatch]:
        b, self._pending = self._pending, None
        return b

    def is_finished(self) -> bool:
        return self.input_done and self._pending is None


class LimitOperator(Operator):
    def __init__(self, count: int):
        self.count = count
        self._remaining = count
        self._pending = None

    def needs_input(self) -> bool:
        return self._remaining > 0 and self._pending is None and super().needs_input()

    def add_input(self, batch: ColumnBatch) -> None:
        batch = batch.compact()
        if batch.num_rows > self._remaining:
            batch = batch.slice(0, self._remaining)
        self._remaining -= batch.num_rows
        self._pending = batch

    def get_output(self):
        b, self._pending = self._pending, None
        return b

    def is_finished(self) -> bool:
        return (self.input_done or self._remaining == 0) and self._pending is None


class DistinctLimitOperator(BufferedInputMixin, Operator):
    """DISTINCT (optionally limited): dedup via the grouping kernel."""

    def __init__(self, count: Optional[int]):
        self.count = count
        self._batches: list[ColumnBatch] = []
        self._result = None
        self._emitted = False

    def add_input(self, batch: ColumnBatch) -> None:
        if batch.num_rows:
            self._batches.append(batch)
            self.account_memory()

    def finish_input(self) -> None:
        super().finish_input()
        if not self.buffered_batches():
            self._emitted = True
            return
        inp = ColumnBatch.concat(self._batches)
        keys = [(np.asarray(c.data),
                 None if c.valid is None else np.asarray(c.valid))
                for c in inp.columns]
        perm, gid, n = K.group_ids(keys)
        # first occurrence of each group (keeps input order stable-ish)
        first = np.full(n, inp.num_rows, dtype=np.int64)
        np.minimum.at(first, np.asarray(gid), np.asarray(perm))
        out = inp.take(np.sort(first))
        if self.count is not None:
            out = out.slice(0, self.count)
        self._result = out
        self.release_memory()

    def get_output(self):
        if self._result is not None and not self._emitted:
            self._emitted = True
            return self._result
        return None

    def is_finished(self) -> bool:
        return self.input_done and self._emitted


# ---------------------------------------------------------------------------
# sinks


class TableWriterOperator(Operator):
    """Writes batches into a connector sink; emits the row count
    (operator/TableWriterOperator.java:68)."""

    def __init__(self, sink: ConnectorPageSink, on_finish=None):
        self.sink = sink
        self.on_finish = on_finish
        self._rows = 0
        self._emitted = False

    def add_input(self, batch: ColumnBatch) -> None:
        batch = batch.compact()
        self._rows += batch.num_rows
        self.sink.append(batch)

    def finish_input(self) -> None:
        super().finish_input()
        fragments = self.sink.finish()
        if self.on_finish is not None:
            self.on_finish(fragments)

    def get_output(self):
        if self.input_done and not self._emitted:
            self._emitted = True
            return ColumnBatch(["rows"], [Column(BIGINT, np.array([self._rows]))])
        return None

    def is_finished(self) -> bool:
        return self.input_done and self._emitted


class OutputCollector(Operator):
    """Terminal sink: buffers result batches for the client."""

    def __init__(self):
        self.batches: list[ColumnBatch] = []

    def add_input(self, batch: ColumnBatch) -> None:
        if batch.num_rows:
            self.batches.append(batch)

    def is_finished(self) -> bool:
        return self.input_done
