"""Driver: moves batches through an operator chain.

Mirrors Trino's Driver.processInternal hot loop (reference:
operator/Driver.java:372 — ``page = current.getOutput(); next.addInput(page)``
per adjacent operator pair, finish propagation, early close on satisfied
LIMITs).  Single-threaded and synchronous: blocking here means an operator
simply declines input until a bridge is ready, and pipelines are executed in
dependency order by the task runner (build pipelines before probe pipelines —
the moral equivalent of HashBuilder blocking LookupJoin via the
LookupSourceFactory future).
"""

from __future__ import annotations

from typing import Sequence

from .operators import Operator

__all__ = ["Driver", "run_pipelines"]


class Driver:
    def __init__(self, operators: Sequence[Operator]):
        assert operators, "empty pipeline"
        self.operators = list(operators)

    def run(self) -> None:
        ops = self.operators
        n = len(ops)
        while not ops[-1].is_finished():
            progressed = False
            for i in range(n - 1):
                cur, nxt = ops[i], ops[i + 1]
                # early close: downstream done (e.g. LIMIT satisfied)
                if nxt.is_finished() and not cur.is_finished():
                    cur.close()
                    progressed = True
                    continue
                if not cur.is_finished() and nxt.needs_input():
                    page = cur.get_output()
                    if page is not None:
                        nxt.add_input(page)
                        progressed = True
                if cur.is_finished() and not nxt.input_done:
                    nxt.finish_input()
                    progressed = True
            if ops[-1].is_finished():
                break
            if not progressed:
                stuck = [type(o).__name__ for o in ops if not o.is_finished()]
                raise RuntimeError(f"driver stalled; unfinished: {stuck}")
        # upstream of an early-finished sink gets closed so sources release
        for op in ops[:-1]:
            if not op.is_finished():
                op.close()


def run_pipelines(pipelines: Sequence[Sequence[Operator]]) -> None:
    """Execute pipelines in dependency order (build sides first)."""
    for p in pipelines:
        Driver(p).run()
