"""Driver: moves batches through an operator chain.

Mirrors Trino's Driver.processInternal hot loop (reference:
operator/Driver.java:372 — ``page = current.getOutput(); next.addInput(page)``
per adjacent operator pair, finish propagation, early close on satisfied
LIMITs).  Single-threaded and synchronous: blocking here means an operator
simply declines input until a bridge is ready, and pipelines are executed in
dependency order by the task runner (build pipelines before probe pipelines —
the moral equivalent of HashBuilder blocking LookupJoin via the
LookupSourceFactory future).
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

from ..spi.errors import GENERIC_INTERNAL_ERROR, TrinoError
from ..telemetry import profiler
from .operators import Operator
from .stats import (EncodingStats, OperatorStats, PipelineStats, QueryStats,
                    ScanIngestStats)

__all__ = ["Driver", "run_pipelines", "collect_scan_stats",
           "collect_encoding_stats"]


def collect_scan_stats(pipelines: Sequence[Sequence[Operator]]
                       ) -> Optional[ScanIngestStats]:
    """Roll up per-ScanOperator ingest counters (None if no scans ran)."""
    total: Optional[ScanIngestStats] = None
    for p in pipelines:
        for op in p:
            ingest = getattr(op, "ingest_stats", None)
            if ingest is not None and ingest.scan_batches:
                if total is None:
                    total = ScanIngestStats()
                total.merge(ingest)
    return total


def collect_encoding_stats(pipelines: Sequence[Sequence[Operator]]
                           ) -> Optional[EncodingStats]:
    """Roll up per-operator compressed-execution counters (None when no
    operator saw an encoded batch)."""
    total: Optional[EncodingStats] = None
    for p in pipelines:
        for op in p:
            enc = getattr(op, "encoding_stats", None)
            if enc is not None and enc.any:
                if total is None:
                    total = EncodingStats()
                total.merge(enc)
    return total


class Driver:
    def __init__(self, operators: Sequence[Operator],
                 stats: Optional[PipelineStats] = None):
        assert operators, "empty pipeline"
        self.operators = list(operators)
        self.stats = stats
        self._names = [type(op).__name__ for op in self.operators]
        if stats is not None:
            stats.operators.extend(
                OperatorStats(name) for name in self._names)

    def _emit(self, i: int, page) -> None:
        """Credit a page moving from operator i to i+1."""
        s = self.stats
        if s is None or page is None:
            return
        src, dst = s.operators[i], s.operators[i + 1]
        src.output_rows += page.num_rows
        src.output_batches += 1
        dst.input_rows += page.num_rows
        dst.input_batches += 1

    def run(self) -> None:
        """Run to completion (single-driver execution)."""
        while True:
            status = self.process()
            if status == "finished":
                return
            if status == "blocked":
                stuck = [type(o).__name__ for o in self.operators
                         if not o.is_finished()]
                raise TrinoError(GENERIC_INTERNAL_ERROR,
                                 f"driver stalled; unfinished: {stuck}")

    def process(self, deadline: float = float("inf")) -> str:
        """One scheduling quantum: move pages until ``deadline`` (a
        time.perf_counter() timestamp), the driver finishes, or no operator
        can make progress.  Returns 'finished' | 'progressed' | 'blocked'
        (blocked = alive but waiting on an external input, e.g. an exchange
        or a bridge).  This is the yieldable unit the time-sharing executor
        schedules (reference: operator/Driver.processFor +
        TimeSharingTaskExecutor quanta)."""
        ops = self.operators
        n = len(ops)
        timed = self.stats is not None
        st = self.stats.operators if timed else None
        # profiler: one wall-clock read + one tuple store per successful
        # page move (no device syncs, no locks).  At TRINO_TPU_PROFILE=full
        # the produced page is blocked-on first, so the enclosing event
        # charges true device time instead of async dispatch time.
        prof = profiler.enabled()
        prof_full = prof and profiler.is_full()
        names = self._names
        any_progress = False
        while not ops[-1].is_finished():
            progressed = False
            for i in range(n - 1):
                cur, nxt = ops[i], ops[i + 1]
                # early close: downstream done (e.g. LIMIT satisfied)
                if nxt.is_finished() and not cur.is_finished():
                    cur.close()
                    progressed = True
                    continue
                if not cur.is_finished() and nxt.needs_input():
                    t0 = time.perf_counter() if timed else 0.0
                    p0 = time.time() if prof else 0.0
                    page = cur.get_output()
                    if timed:
                        st[i].wall_s += time.perf_counter() - t0
                    if page is not None:
                        if prof:
                            if prof_full:
                                profiler.sync_batch(page)
                            profiler.event(profiler.OPERATOR, names[i], p0,
                                           rows=page.num_rows)
                        t0 = time.perf_counter() if timed else 0.0
                        p0 = time.time() if prof else 0.0
                        nxt.add_input(page)
                        if timed:
                            st[i + 1].wall_s += time.perf_counter() - t0
                        if prof:
                            profiler.event(profiler.OPERATOR, names[i + 1],
                                           p0, rows=page.num_rows)
                        self._emit(i, page)
                        progressed = True
                if cur.is_finished() and not nxt.input_done:
                    if i + 2 == n:
                        # pre-finish barrier: deferred masked-lane errors
                        # must surface BEFORE the sink marks its stream
                        # finished — a streaming consumer could otherwise
                        # observe a complete, "successful" result (NULL
                        # lanes) from a task that is about to fail
                        from ..ops.expr import check_error_scalars

                        check_error_scalars([
                            e for op in ops
                            for e in getattr(op, "pending_errors", ())])
                    t0 = time.perf_counter() if timed else 0.0
                    p0 = time.time() if prof else 0.0
                    nxt.finish_input()
                    if timed:
                        st[i + 1].wall_s += time.perf_counter() - t0
                    if prof:
                        # finish is where blocking operators (agg flush,
                        # sort, join build seal) do their heavy lifting
                        profiler.event(profiler.OPERATOR,
                                       names[i + 1] + ".finish", p0)
                    progressed = True
            if ops[-1].is_finished():
                break
            if not progressed:
                return "progressed" if any_progress else "blocked"
            any_progress = True
            if time.perf_counter() >= deadline:
                return "progressed"
        # upstream of an early-finished sink gets closed so sources release
        for op in ops[:-1]:
            if not op.is_finished():
                op.close()
        return "finished"


def run_pipelines(pipelines: Sequence[Sequence[Operator]],
                  stats: Optional[QueryStats] = None) -> None:
    """Execute pipelines in dependency order (build sides first).
    Pipelines belonging to one local-exchange cluster (tagged with the same
    ``_concurrent_group`` on their source operator — producers, parallel
    aggregation drivers AND the consumer chain) run on concurrent threads
    with bounded buffers between them: a full buffer parks the producer, an
    empty one parks the consumer, so memory stays bounded and the stages
    genuinely pipeline (numpy/XLA release the GIL inside kernels).  The
    legacy concurrent-union grouping (UnionSinkOperator with a concurrent
    bridge) is kept for plain UNION chains."""
    import threading

    from . import syncguard
    from .operators import UnionSinkOperator

    sync_before = syncguard.snapshot() if stats is not None else None

    def run_one(p, stop=None) -> None:
        ps = None
        if stats is not None:
            ps = PipelineStats()
            stats.pipelines.append(ps)
        Driver(p, ps).run()

    def run_parked(p, stop=None) -> None:
        """Drive to completion, sleeping briefly while parked on a bounded
        buffer (the thread-pool analogue of isBlocked() futures).  ``stop``
        aborts the loop when a sibling pipeline of the cluster failed."""
        ps = None
        if stats is not None:
            ps = PipelineStats()
            stats.pipelines.append(ps)
        d = Driver(p, ps)
        while True:
            if stop is not None and stop.is_set():
                return
            status = d.process()
            if status == "finished":
                return
            time.sleep(2e-4)

    def run_group(group, runner) -> None:
        from ..telemetry import profiler

        errors: list[BaseException] = []
        stop = threading.Event()
        # group threads inherit the spawning task thread's profiler
        # identity, so their operator events attribute to the right query
        prof_ctx = profiler.capture_context()

        def wrapped(q):
            try:
                profiler.apply_context(prof_ctx)
                runner(q, stop)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                stop.set()  # unpark siblings so the group can unwind

        threads = [threading.Thread(target=wrapped, args=(q,),
                                    daemon=True) for q in group]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

    i = 0
    n = len(pipelines)
    while i < n:
        p = pipelines[i]
        group = [p]
        gid = getattr(p[0], "_concurrent_group", None)
        if gid is not None:
            while (i + 1 < n and getattr(
                    pipelines[i + 1][0], "_concurrent_group", None) is gid):
                i += 1
                group.append(pipelines[i])
            run_group(group, run_parked)
            i += 1
            continue
        if isinstance(p[-1], UnionSinkOperator) and p[-1].bridge.concurrent:
            bridge = p[-1].bridge
            while (i + 1 < n
                   and isinstance(pipelines[i + 1][-1], UnionSinkOperator)
                   and pipelines[i + 1][-1].bridge is bridge):
                i += 1
                group.append(pipelines[i])
        if len(group) > 1:
            run_group(group, run_one)
        else:
            run_one(p)
        i += 1

    if stats is not None:
        ingest = collect_scan_stats(pipelines)
        if ingest is not None:
            stats.merge_scan(ingest)
        enc = collect_encoding_stats(pipelines)
        if enc is not None:
            stats.merge_encoding(enc)
        stats.merge_sync(syncguard.take_delta(sync_before))

    # deferred masked-lane expression errors (DIVISION_BY_ZERO, overflow...)
    # surface here: ONE batched scalar fetch across every operator of the
    # task, raising before any result is returned (ops/expr.py error channel)
    from ..ops.expr import check_error_scalars

    check_error_scalars([
        e for p in pipelines for op in p
        for e in getattr(op, "pending_errors", ())
    ])
