"""Disk spill tier: serialized pages in temp files.

The second spill tier below HBM->host eviction (exec/revoking.py):
when an operator's HOST-buffered bytes exceed the session's
``spill_to_disk_bytes``, buffered batches are written as compressed serde
pages (execution/serde.py) to a spill file and read back at finish.
Mirrors the reference's FileSingleStreamSpiller.java:57 +
GenericSpillerFactory (one file per spilling operator, pages appended
length-prefixed, eagerly deleted on close).
"""

from __future__ import annotations

import os
import tempfile
from typing import Iterator, Optional

from ..spi.batch import ColumnBatch
from ..execution.serde import deserialize_batch, serialize_batch

__all__ = ["Spiller"]


class Spiller:
    """Append-only page spill file for one operator."""

    def __init__(self, spill_dir: Optional[str] = None):
        self._dir = spill_dir
        self._file = None
        self.pages_spilled = 0
        self.bytes_spilled = 0

    def spill(self, batch: ColumnBatch) -> None:
        from ..execution.serde import write_frame
        from ..telemetry import profiler

        t0 = profiler.now() if profiler.enabled() else 0.0
        if self._file is None:
            fd, path = tempfile.mkstemp(prefix="trino-tpu-spill-",
                                        suffix=".bin", dir=self._dir)
            self._file = os.fdopen(fd, "w+b")
            os.unlink(path)  # anonymous: vanishes with the fd on any exit
        page = serialize_batch(batch)
        write_frame(self._file, page)
        self.pages_spilled += 1
        self.bytes_spilled += len(page)
        if t0:
            profiler.event(profiler.SPILL, "spill.write", t0,
                           rows=batch.num_rows, bytes=len(page))

    def read_back(self) -> Iterator[ColumnBatch]:
        from ..execution.serde import iter_frames
        from ..telemetry import profiler

        if self._file is None:
            return
        self._file.seek(0)
        for frame in iter_frames(self._file):
            t0 = profiler.now() if profiler.enabled() else 0.0
            b = deserialize_batch(frame)
            if t0:
                profiler.event(profiler.SPILL, "spill.read_back", t0,
                               rows=b.num_rows, bytes=len(frame))
            yield b

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
