"""Local execution planner: plan tree → operator pipelines.

The LocalExecutionPlanner equivalent (reference: sql/planner/
LocalExecutionPlanner.java:403 — visitTableScan:2088, visitAggregation:1876,
visitJoin:2449): walks the optimized plan bottom-up building one operator
chain per pipeline; a join's build side becomes its own pipeline connected
through a JoinBridge (mirrors createSubContext + JoinBridge wiring at
LocalExecutionPlanner.java:2613).

Pipelines come back in dependency order: every build pipeline precedes the
pipeline that probes it, so a sequential run is correct (concurrent drivers
arrive with the task executor).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..connectors.catalog import Catalog
from ..planner import plan as P
from ..spi.batch import Column, ColumnBatch
from ..spi.types import Type
from ..sql.ir import InputRef
from .dynamic_filter import DynamicFilterHolder
from .revoking import TaskMemoryContext
from .operators import (
    BufferedInputMixin,
    DistinctLimitOperator,
    FilterProjectOperator,
    GroupIdOperator,
    HashAggregationOperator,
    JoinBridge,
    JoinBuildSink,
    LimitOperator,
    LocalUnionBridge,
    LookupJoinOperator,
    Operator,
    OutputCollector,
    RenameOperator,
    ReplicateOperator,
    ScanOperator,
    SemiJoinOperator,
    TableFunctionOperator,
    SortOperator,
    TableWriterOperator,
    TopNOperator,
    UnnestOperator,
    UnionSinkOperator,
    UnionSourceOperator,
    ValuesOperator,
    WindowOperator,
    plan_lazy_scan,
)

__all__ = ["LocalExecutionPlan", "LocalPlanner"]


class LocalExecutionPlan:
    def __init__(self, pipelines: list[list[Operator]], collector: OutputCollector,
                 output_names: Sequence[str], output_types: Sequence[Type]):
        self.pipelines = pipelines
        self.collector = collector
        self.output_names = list(output_names)
        self.output_types = list(output_types)


class LocalPlanner:
    def __init__(self, catalog: Catalog, splits_per_node: int = 4,
                 node_count: int = 1, task_index: int = 0,
                 task_count: int = 1, remote_clients=None,
                 dynamic_filtering: bool = True,
                 hbm_limit_bytes: int = 16 << 30,
                 spill_to_disk_bytes: int = 0,
                 task_concurrency: int = 1):
        self.task_concurrency = task_concurrency
        self.catalog = catalog
        self.splits_per_node = splits_per_node
        self.node_count = node_count
        # distributed: this task's share of splits + exchange clients per
        # upstream fragment id (filled by the stage scheduler)
        self.task_index = task_index
        self.task_count = task_count
        self.remote_clients = remote_clients or {}
        self.dynamic_filtering = dynamic_filtering
        # per-task HBM pool: blocking operators reserve buffered device
        # bytes as revocable memory (exec/revoking.py)
        self.memory = TaskMemoryContext(hbm_limit_bytes, spill_to_disk_bytes)
        self.pipelines: list[list[Operator]] = []

    def plan(self, root: P.PlanNode) -> LocalExecutionPlan:
        chain = self._chain(root)
        collector = OutputCollector()
        chain.append(collector)
        self.pipelines.append(chain)
        if self.task_concurrency > 1:
            self.pipelines = [
                q for p in self.pipelines for q in self._parallelize(p)]
        for p in self.pipelines:
            plan_lazy_scan(p)
            for op in p:
                if isinstance(op, BufferedInputMixin):
                    op.attach_memory(self.memory)
        return LocalExecutionPlan(
            self.pipelines, collector, root.output_names, root.output_types)

    def _parallelize(self, pipeline: list[Operator]) -> list[list[Operator]]:
        """Intra-task parallelism (LocalExchange.java:67 +
        AddLocalExchanges.java:111): a pipeline whose source is a multi-split
        scan forks into ``task_concurrency`` parallel driver chains.  Row-
        parallel operators (filter/project, INNER/LEFT/SINGLE lookup joins,
        semi joins — all probing the shared build bridge) clone into every
        chain; at the first grouped aggregation the rows cross a bounded
        HASH local exchange into ``task_concurrency`` parallel aggregation
        drivers (disjoint group spaces, so their outputs simply concatenate);
        everything further downstream runs in one consumer chain behind a
        GATHER exchange.  All pipelines of one exchange cluster are tagged
        with a ``_concurrent_group`` id — the driver runner executes the
        whole cluster concurrently with backpressure from the bounded
        buffers."""
        if not isinstance(pipeline[0], ScanOperator):
            return [pipeline]
        scan = pipeline[0]
        c = min(self.task_concurrency, len(scan.splits))
        if c < 2:
            return [pipeline]
        from .local_exchange import (
            GATHER,
            HASH,
            LocalExchange,
            LocalExchangeSinkOperator,
            LocalExchangeSourceOperator,
        )

        def clone(op: Operator) -> Optional[Operator]:
            if isinstance(op, FilterProjectOperator):
                return FilterProjectOperator(
                    op.predicate, op.projections,
                    op.output_names, op.output_types)
            if isinstance(op, LookupJoinOperator) and op.join_type in (
                    "INNER", "LEFT", "SINGLE") and op.left_keys:
                return LookupJoinOperator(
                    op.bridge, op.left_keys, op.join_type, op.residual,
                    op.output_names, op.output_types)
            if isinstance(op, SemiJoinOperator) and op.source_keys:
                return SemiJoinOperator(
                    op.bridge, op.source_keys, op.null_aware, op.residual,
                    op.output_names, op.output_types)
            return None

        prefix = [scan]
        for op in pipeline[1:]:
            if clone(op) is not None:
                prefix.append(op)
            else:
                break
        rest = pipeline[len(prefix):]
        if not rest:  # nothing downstream to feed (shouldn't happen)
            return [pipeline]
        last = prefix[-1]
        names = (scan.columns if last is scan else last.output_names)

        # partition point: grouped aggregation -> HASH exchange + c clones
        agg = rest[0] if (isinstance(rest[0], HashAggregationOperator)
                          and rest[0].group_keys) else None

        gid = object()  # unique tag for this exchange cluster

        def tag(p: list[Operator]) -> list[Operator]:
            p[0]._concurrent_group = gid
            return p

        chains: list[list[Operator]] = []
        exch1 = LocalExchange(
            c, c if agg is not None else 1,
            HASH if agg is not None else GATHER,
            key_channels=(agg.group_keys if agg is not None else ()))
        for i in range(c):
            shard = ScanOperator(
                scan.connector, scan.splits[i::c], scan.columns,
                dynamic_filters=scan.dynamic_filters,
                constraint=scan.constraint, limit=scan.limit)
            ops: list[Operator] = [shard]
            ops += [clone(op) for op in prefix[1:]]
            ops.append(LocalExchangeSinkOperator(exch1, i, names))
            chains.append(tag(ops))
        if agg is None:
            consumer = tag([LocalExchangeSourceOperator(exch1, 0)] + rest)
            return chains + [consumer]
        gather = LocalExchange(c, 1, GATHER)
        for j in range(c):
            agg_clone = HashAggregationOperator(
                agg.group_keys, agg.aggs, agg.output_names,
                agg.output_types, agg.step)
            chains.append(tag([
                LocalExchangeSourceOperator(exch1, j), agg_clone,
                LocalExchangeSinkOperator(gather, j, agg.output_names)]))
        consumer = tag([LocalExchangeSourceOperator(gather, 0)] + rest[1:])
        return chains + [consumer]

    # ------------------------------------------------------------------
    def _chain(self, node: P.PlanNode) -> list[Operator]:
        if isinstance(node, P.TableScan):
            conn = self.catalog.connector(node.catalog)
            splits = conn.get_splits(
                node.table, self.splits_per_node, self.node_count)
            mine = [s for i, s in enumerate(splits)
                    if i % self.task_count == self.task_index]
            return [ScanOperator(conn, mine, node.columns,
                                 constraint=node.constraint,
                                 limit=node.limit)]

        if isinstance(node, P.RemoteSource):
            from ..execution.collective_exchange import (
                CollectiveRepartitionExchange,
                CollectiveSourceOperator,
            )
            from ..execution.task import (
                MergeSourceOperator,
                RemoteExchangeSourceOperator,
            )

            client = self.remote_clients[node.fragment_id]
            if isinstance(client, CollectiveRepartitionExchange):
                return [CollectiveSourceOperator(client, self.task_index)]
            if isinstance(client, list):  # MERGE: per-producer streams
                return [MergeSourceOperator(
                    client, node.sort_keys,
                    node.output_names, node.output_types)]
            return [RemoteExchangeSourceOperator(client)]

        if isinstance(node, P.Filter):
            chain = self._chain(node.source)
            last = chain[-1] if chain else None
            if (isinstance(last, FilterProjectOperator)
                    and last.projections is None):
                # Filter over Filter: AND the predicates into one program
                from ..spi.types import BOOLEAN
                from ..sql.ir import Call

                pred = node.predicate if last.predicate is None else Call(
                    BOOLEAN, "$and", (last.predicate, node.predicate))
                chain[-1] = FilterProjectOperator(
                    pred, None, node.output_names, node.output_types)
                return chain
            chain.append(FilterProjectOperator(
                node.predicate, None, node.output_names, node.output_types))
            return chain

        if isinstance(node, P.Project):
            chain = self._chain(node.source)
            last = chain[-1] if chain else None
            if (isinstance(last, FilterProjectOperator)
                    and last.projections is None):
                # Project over Filter: ONE fused filter+project program per
                # batch instead of two (ScanFilterAndProject fusion —
                # reference: operator/ScanFilterAndProjectOperator.java:68)
                chain[-1] = FilterProjectOperator(
                    last.predicate, node.expressions,
                    node.output_names, node.output_types)
                return chain
            chain.append(FilterProjectOperator(
                None, node.expressions, node.output_names, node.output_types))
            return chain

        if isinstance(node, P.Aggregate):
            if (node.step == "FINAL"
                    and isinstance(node.source, P.RemoteSource)):
                from ..execution.stage_compiler import (
                    FusedStageExec,
                    FusedStageSourceOperator,
                )

                client = self.remote_clients.get(node.source.fragment_id)
                if isinstance(client, FusedStageExec):
                    # whole-stage compilation: the producer stage already
                    # ran PARTIAL + all_to_all + FINAL inside one jitted
                    # program; this pipeline just takes its device shard
                    return [FusedStageSourceOperator(client,
                                                     self.task_index)]
            chain = self._chain(node.source)
            chain.append(HashAggregationOperator(
                node.group_keys, node.aggregates,
                node.output_names, node.output_types, node.step))
            return chain

        if isinstance(node, P.GroupId):
            chain = self._chain(node.source)
            chain.append(GroupIdOperator(
                node.key_channels, node.passthrough, node.sets,
                node.output_names, node.output_types))
            return chain

        if isinstance(node, P.Unnest):
            chain = self._chain(node.source)
            chain.append(UnnestOperator(
                node.replicate, node.unnest_channels, node.ordinality,
                node.output_names, node.output_types))
            return chain

        if isinstance(node, P.Join):
            bridge = JoinBridge()
            # dynamic filtering: INNER/RIGHT probe rows that cannot match are
            # droppable, so the build-side key domain prunes the probe scan
            # (exec/dynamic_filter.py; server/DynamicFilterService.java:105)
            holders = [None] * len(node.right_keys)
            scan_attach = []
            if (self.dynamic_filtering and node.left_keys
                    and node.join_type in ("INNER", "RIGHT")):
                for k, lch in enumerate(node.left_keys):
                    col = _trace_to_scan_col(node.left, lch)
                    if col is not None:
                        holders[k] = DynamicFilterHolder()
                        scan_attach.append((col, holders[k]))
            build = self._chain(node.right)
            build.append(JoinBuildSink(
                bridge, node.right_keys,
                node.right.output_types, node.right.output_names,
                dynamic_filter_holders=holders))
            self.pipelines.append(build)
            chain = self._chain(node.left)
            if scan_attach and isinstance(chain[0], ScanOperator):
                chain[0].dynamic_filters.extend(scan_attach)
            chain.append(LookupJoinOperator(
                bridge, node.left_keys, node.join_type, node.residual,
                node.output_names, node.output_types))
            return chain

        if isinstance(node, P.SemiJoin):
            bridge = JoinBridge()
            build = self._chain(node.filter_source)
            build.append(JoinBuildSink(
                bridge, node.filter_keys,
                node.filter_source.output_types, node.filter_source.output_names))
            self.pipelines.append(build)
            chain = self._chain(node.source)
            chain.append(SemiJoinOperator(
                bridge, node.source_keys, node.null_aware, node.residual,
                node.output_names, node.output_types))
            return chain

        if isinstance(node, P.Union):
            bridge = LocalUnionBridge(len(node.sources))
            for src in node.sources:
                chain = self._chain(src)
                chain.append(UnionSinkOperator(bridge, node.output_names))
                self.pipelines.append(chain)
            return [UnionSourceOperator(bridge)]

        if isinstance(node, P.MatchRecognize):
            from .match_recognize import MatchRecognizeOperator

            chain = self._chain(node.source)
            chain.append(MatchRecognizeOperator(
                node.partition_channels, node.order_keys, node.pattern,
                node.defines, node.measures, node.skip_past,
                node.output_names, node.output_types,
                node.source.output_names))
            return chain

        if isinstance(node, P.Window):
            chain = self._chain(node.source)
            chain.append(WindowOperator(
                node.partition_keys, node.order_keys, node.functions,
                node.output_names, node.output_types))
            return chain

        if isinstance(node, P.Sort):
            chain = self._chain(node.source)
            chain.append(SortOperator(node.keys))
            return chain

        if isinstance(node, P.TopN):
            chain = self._chain(node.source)
            chain.append(TopNOperator(node.count, node.keys))
            return chain

        if isinstance(node, P.Limit):
            chain = self._chain(node.source)
            chain.append(LimitOperator(node.count))
            return chain

        if isinstance(node, P.Replicate):
            chain = self._chain(node.source)
            chain.append(ReplicateOperator(node.count_channel))
            return chain

        if isinstance(node, P.DistinctLimit):
            chain = self._chain(node.source)
            chain.append(DistinctLimitOperator(node.count))
            return chain

        if isinstance(node, P.Values):
            batch = _values_batch(node)
            return [ValuesOperator(batch)]

        if isinstance(node, P.TableFunctionScan):
            return [TableFunctionOperator(node.bound, node.output_names)]

        if isinstance(node, P.Output):
            chain = self._chain(node.source)
            chain.append(RenameOperator(node.output_names))
            return chain

        if isinstance(node, P.Exchange):
            # single-node: exchanges are pass-through; the distributed task
            # runner replaces these with collective/buffered edges
            return self._chain(node.source)

        if isinstance(node, P.TableWriter):
            chain = self._chain(node.source)
            conn = self.catalog.connector(node.catalog)
            try:
                schema = conn.get_table_schema(node.table)
            except KeyError:  # CTAS: create target from source schema
                from ..spi.connector import ColumnSchema, TableSchema
                schema = TableSchema(node.table, tuple(
                    ColumnSchema(n, t) for n, t in
                    zip(node.source.output_names, node.source.output_types)))
                try:
                    conn.create_table(schema)
                except ValueError:
                    # parallel writer tasks race to create the CTAS target;
                    # first one wins (scaled writers)
                    schema = conn.get_table_schema(node.table)
            # INSERT maps select output to table columns by POSITION
            chain.append(RenameOperator([c.name for c in schema.columns]))
            sink = conn.create_page_sink(node.table)
            chain.append(TableWriterOperator(
                sink,
                on_finish=lambda frags: conn.finish_insert(node.table, frags)))
            return chain

        raise NotImplementedError(f"no operator for {type(node).__name__}")


def _trace_to_scan_col(node: P.PlanNode, ch: int) -> Optional[int]:
    """Map an output channel down the probe-side left spine to a TableScan
    column index, or None if the channel is computed / crosses a remote or
    union boundary.  Descends only paths whose rows pass through unchanged
    (a dropped probe row cannot change other rows' results)."""
    while True:
        if isinstance(node, P.TableScan):
            return ch
        if isinstance(node, (P.Filter, P.Exchange)):
            node = node.source
            continue
        if isinstance(node, P.Project):
            e = node.expressions[ch]
            if isinstance(e, InputRef):
                node, ch = node.source, e.index
                continue
            return None
        if isinstance(node, P.Join):
            lw = len(node.left.output_types)
            if ch < lw:
                node = node.left
                continue
            return None
        if isinstance(node, P.SemiJoin):
            if ch < len(node.source.output_types):
                node = node.source
                continue
            return None
        return None


def _values_batch(node: P.Values) -> ColumnBatch:
    cols = []
    for i, t in enumerate(node.output_types):
        vals = [row[i] for row in node.rows]
        cols.append(Column.from_values(t, vals))
    return ColumnBatch(list(node.output_names), cols)
