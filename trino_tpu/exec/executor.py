"""Time-sharing task executor: bounded workers + MLFQ quanta.

The reference runs every worker's drivers on a fixed thread pool where each
driver gets a time quantum and yields back to a multilevel feedback queue
prioritized by accumulated CPU time (execution/executor/timesharing/
TimeSharingTaskExecutor.java:85, MultilevelSplitQueue.java:39).  This is
that scheduler in miniature: N worker threads, tasks requeue after each
quantum at a level chosen by accumulated wall time, so short queries finish
ahead of long-running scans instead of waiting behind a thread-per-task
free-for-all.

Drivers yield via Driver.process(deadline); exchange sources are switched
to non-blocking polls so a waiting consumer parks (requeue) instead of
pinning a worker — which would deadlock a bounded pool.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Optional, Sequence

from .driver import Driver
from .stats import PipelineStats, QueryStats

__all__ = ["TimeSharingTaskExecutor", "TaskHandle"]

# accumulated-seconds thresholds for MLFQ levels (reference:
# MultilevelSplitQueue.LEVEL_THRESHOLD_SECONDS 0,1,10,60,300 scaled down)
_LEVELS = (0.0, 0.5, 2.0, 10.0, 60.0)
_QUANTUM_S = 0.25


# blocked tasks park BELOW every working level: a consumer waiting on its
# producer must never outrank it on the strict-priority heap (starvation
# observed otherwise: level-0 blocked consumers churned ahead of level-1+
# producers)
_BLOCKED_LEVEL = len(_LEVELS)


def _level_of(elapsed: float) -> int:
    lvl = 0
    for i, t in enumerate(_LEVELS):
        if elapsed >= t:
            lvl = i
    return lvl


class TaskHandle:
    """One task = its pipelines executed in dependency order, sharing an
    accumulated-time budget for MLFQ placement."""

    def __init__(self, pipelines: Sequence[Sequence],
                 stats: Optional[QueryStats] = None):
        self.drivers: list[Driver] = []
        for p in pipelines:
            ps = None
            if stats is not None:
                ps = PipelineStats()
                stats.pipelines.append(ps)
            self.drivers.append(Driver(p, ps))
        self._current = 0
        self.elapsed = 0.0
        self.blocked_streak = 0
        self.error: Optional[BaseException] = None
        self.done = threading.Event()

    def process_quantum(self) -> str:
        """-> 'finished' | 'progressed' | 'blocked'."""
        t0 = time.perf_counter()
        try:
            deadline = t0 + _QUANTUM_S
            while self._current < len(self.drivers):
                status = self.drivers[self._current].process(deadline)
                if status == "finished":
                    self._current += 1
                    if time.perf_counter() >= deadline:
                        break
                    continue
                return status
            if self._current >= len(self.drivers):
                self.done.set()
                return "finished"
            return "progressed"
        except BaseException as e:  # noqa: BLE001 — stored for the caller
            self.error = e
            self.done.set()
            return "finished"
        finally:
            self.elapsed += time.perf_counter() - t0


class TimeSharingTaskExecutor:
    def __init__(self, num_workers: int = 4):
        self.num_workers = num_workers
        self._heap: list = []  # (level, seq, handle)
        self._seq = itertools.count()
        self._cv = threading.Condition()
        self._shutdown = False
        self._threads = [
            threading.Thread(target=self._worker, name=f"task-executor-{i}",
                             daemon=True)
            for i in range(num_workers)
        ]
        for t in self._threads:
            t.start()

    def submit(self, pipelines: Sequence[Sequence],
               stats: Optional[QueryStats] = None) -> TaskHandle:
        # non-blocking sources: a parked consumer must release its worker
        for p in pipelines:
            for op in p:
                if hasattr(op, "blocking"):
                    op.blocking = False
        handle = TaskHandle(pipelines, stats)
        self._enqueue(handle, 0)
        return handle

    def _enqueue(self, handle: TaskHandle, level: int) -> None:
        with self._cv:
            heapq.heappush(self._heap, (level, next(self._seq), handle))
            self._cv.notify()

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._heap and not self._shutdown:
                    self._cv.wait(timeout=0.1)
                if self._shutdown:
                    return
                _, _, handle = heapq.heappop(self._heap)
            status = handle.process_quantum()
            if status == "finished":
                continue
            if status == "blocked":
                # sink below the producer this task waits on, deeper with
                # every consecutive block — but never permanently below
                # other queries' work (a parked-forever bottom level would
                # trade intra-query starvation for cross-query starvation)
                handle.blocked_streak += 1
                level = min(_BLOCKED_LEVEL,
                            _level_of(handle.elapsed) + handle.blocked_streak)
                time.sleep(0.001)
                self._enqueue(handle, level)
                continue
            handle.blocked_streak = 0
            self._enqueue(handle, _level_of(handle.elapsed))

    def shutdown(self) -> None:
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5)
