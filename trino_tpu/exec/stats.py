"""Per-operator execution statistics (the OperatorStats equivalent).

Mirrors the role of operator/OperatorStats.java + OperationTimer: the Driver
credits wall time and row/batch counts to each operator as it moves pages, and
EXPLAIN ANALYZE renders the totals per pipeline (reference:
operator/ExplainAnalyzeOperator.java:36, sql/planner/planprinter/PlanPrinter).

Row counts use physical batch rows (padded slots included) so collecting
stats never forces a device sync on the hot path.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class OperatorStats:
    name: str
    input_rows: int = 0
    output_rows: int = 0
    input_batches: int = 0
    output_batches: int = 0
    wall_s: float = 0.0


@dataclass
class PipelineStats:
    operators: list[OperatorStats] = field(default_factory=list)


@dataclass
class QueryStats:
    """One query's (or one task's) operator stats, per pipeline."""

    label: str = ""
    pipelines: list[PipelineStats] = field(default_factory=list)

    def text(self) -> str:
        lines = []
        if self.label:
            lines.append(self.label)
        for i, p in enumerate(self.pipelines):
            lines.append(f"  pipeline {i}:")
            for op in p.operators:
                lines.append(
                    f"    {op.name}: {op.wall_s * 1e3:.1f} ms, "
                    f"in {op.input_rows} rows/{op.input_batches} batches, "
                    f"out {op.output_rows} rows/{op.output_batches} batches")
        return "\n".join(lines)
