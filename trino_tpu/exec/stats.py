"""Per-operator execution statistics (the OperatorStats equivalent).

Mirrors the role of operator/OperatorStats.java + OperationTimer: the Driver
credits wall time and row/batch counts to each operator as it moves pages, and
EXPLAIN ANALYZE renders the totals per pipeline (reference:
operator/ExplainAnalyzeOperator.java:36, sql/planner/planprinter/PlanPrinter).

Row counts use physical batch rows (padded slots included) so collecting
stats never forces a device sync on the hot path.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class ScanIngestStats:
    """Counters for the async scan-ingest pipeline (prefetch + coalesce +
    device staging).  One instance per ScanOperator; ``merge`` folds shard
    instances into the query-level roll-up rendered by QueryStats.text()."""

    scan_bytes: int = 0          # host bytes produced by connector sources
    scan_rows: int = 0
    scan_batches: int = 0        # raw connector batches
    coalesced_batches: int = 0   # merged batches emitted to the pipeline
    coalesced_rows: int = 0
    staged_batches: int = 0      # batches dispatched to device
    splits_opened: int = 0
    source_read_s: float = 0.0   # time inside connector get_next_batch
    consumer_wait_s: float = 0.0  # consumer blocked waiting on prefetch
    stage_s: float = 0.0         # device_put dispatch time
    queue_depth_max: int = 0
    queue_depth_sum: int = 0
    queue_samples: int = 0
    prefetch_enabled: bool = False
    first_batch_t: float | None = None
    last_batch_t: float | None = None

    def observe_batch(self, nbytes: int, rows: int) -> None:
        now = time.perf_counter()
        if self.first_batch_t is None:
            self.first_batch_t = now
        self.last_batch_t = now
        self.scan_bytes += nbytes
        self.scan_rows += rows
        self.scan_batches += 1

    @property
    def wall_s(self) -> float:
        if self.first_batch_t is None or self.last_batch_t is None:
            return 0.0
        return self.last_batch_t - self.first_batch_t

    @property
    def gbps(self) -> float:
        """Scan ingest GB/s over the first->last batch window."""
        w = self.wall_s
        return (self.scan_bytes / w) / 1e9 if w > 0 else 0.0

    @property
    def queue_depth_avg(self) -> float:
        return self.queue_depth_sum / self.queue_samples if self.queue_samples else 0.0

    def merge(self, other: "ScanIngestStats") -> None:
        self.scan_bytes += other.scan_bytes
        self.scan_rows += other.scan_rows
        self.scan_batches += other.scan_batches
        self.coalesced_batches += other.coalesced_batches
        self.coalesced_rows += other.coalesced_rows
        self.staged_batches += other.staged_batches
        self.splits_opened += other.splits_opened
        self.source_read_s += other.source_read_s
        self.consumer_wait_s += other.consumer_wait_s
        self.stage_s += other.stage_s
        self.queue_depth_max = max(self.queue_depth_max, other.queue_depth_max)
        self.queue_depth_sum += other.queue_depth_sum
        self.queue_samples += other.queue_samples
        self.prefetch_enabled = self.prefetch_enabled or other.prefetch_enabled
        # overall window spans the earliest first batch to the latest last
        for t in (other.first_batch_t,):
            if t is not None and (self.first_batch_t is None or t < self.first_batch_t):
                self.first_batch_t = t
        for t in (other.last_batch_t,):
            if t is not None and (self.last_batch_t is None or t > self.last_batch_t):
                self.last_batch_t = t

    def text(self) -> str:
        mode = "prefetch" if self.prefetch_enabled else "sync"
        return (
            f"scan[{mode}]: {self.scan_bytes / 1e9:.3f} GB "
            f"({self.scan_rows} rows, {self.scan_batches} batches -> "
            f"{self.coalesced_batches} coalesced) @ {self.gbps:.2f} GB/s, "
            f"queue depth avg {self.queue_depth_avg:.1f} max {self.queue_depth_max}, "
            f"read {self.source_read_s * 1e3:.1f} ms / wait "
            f"{self.consumer_wait_s * 1e3:.1f} ms / stage {self.stage_s * 1e3:.1f} ms"
        )


@dataclass
class ResilienceStats:
    """Counters for the query-level resilience layer (retry_policy=QUERY,
    heartbeat detection, worker replacement, exchange backoff) — the
    QueryStats/tracing surface of execution/failure_detector.py and the
    remote runner's retry loop."""

    query_retries: int = 0
    backoff_waits: int = 0
    backoff_wait_s: float = 0.0
    blacklisted_workers: int = 0
    worker_replacements: int = 0
    heartbeat_transitions: int = 0
    exchange_fetch_failures: int = 0
    exchange_backoff_trips: int = 0

    def merge(self, other: "ResilienceStats") -> None:
        self.query_retries += other.query_retries
        self.backoff_waits += other.backoff_waits
        self.backoff_wait_s += other.backoff_wait_s
        self.blacklisted_workers += other.blacklisted_workers
        self.worker_replacements += other.worker_replacements
        self.heartbeat_transitions += other.heartbeat_transitions
        self.exchange_fetch_failures += other.exchange_fetch_failures
        self.exchange_backoff_trips += other.exchange_backoff_trips

    @classmethod
    def delta(cls, after: "ResilienceStats",
              before: "ResilienceStats") -> "ResilienceStats":
        """after - before, field-wise (runner counters are cumulative; a
        query's own numbers are the delta across its retry loop)."""
        return cls(
            query_retries=after.query_retries - before.query_retries,
            backoff_waits=after.backoff_waits - before.backoff_waits,
            backoff_wait_s=after.backoff_wait_s - before.backoff_wait_s,
            blacklisted_workers=(after.blacklisted_workers
                                 - before.blacklisted_workers),
            worker_replacements=(after.worker_replacements
                                 - before.worker_replacements),
            heartbeat_transitions=(after.heartbeat_transitions
                                   - before.heartbeat_transitions),
            exchange_fetch_failures=(after.exchange_fetch_failures
                                     - before.exchange_fetch_failures),
            exchange_backoff_trips=(after.exchange_backoff_trips
                                    - before.exchange_backoff_trips),
        )

    @property
    def any(self) -> bool:
        return any((self.query_retries, self.backoff_waits,
                    self.blacklisted_workers, self.worker_replacements,
                    self.heartbeat_transitions, self.exchange_fetch_failures,
                    self.exchange_backoff_trips))

    def text(self) -> str:
        return (
            f"resilience: {self.query_retries} query retries "
            f"({self.backoff_waits} backoff waits, "
            f"{self.backoff_wait_s * 1e3:.0f} ms), "
            f"{self.blacklisted_workers} blacklists, "
            f"{self.worker_replacements} worker replacements, "
            f"{self.heartbeat_transitions} heartbeat transitions, "
            f"{self.exchange_fetch_failures} exchange fetch failures "
            f"({self.exchange_backoff_trips} backoff trips)"
        )


@dataclass
class FusedStageStats:
    """Counters for whole-stage GSPMD compilation (execution/stage_compiler.py):
    how many batches the fused accumulate program absorbed, how often the
    shape-bucket cache hit vs traced, and how many seam merges / legacy
    fallbacks ran.  One instance per FusedStageExec; ``merge`` folds the
    per-sink instances into the query-level roll-up."""

    stages: int = 0            # fused stage seams that executed
    compiles: int = 0          # distinct (program, bucket) traces
    cache_hits: int = 0        # jitted calls served by an existing trace
    jit_calls: int = 0         # accumulate-program dispatches (one per batch)
    batches: int = 0           # input batches absorbed
    input_rows: int = 0        # physical rows (padded slots included)
    merges: int = 0            # seam merge programs executed (one per stage)
    fallbacks: int = 0         # overflow -> legacy per-operator re-runs

    def merge(self, other: "FusedStageStats") -> None:
        self.stages += other.stages
        self.compiles += other.compiles
        self.cache_hits += other.cache_hits
        self.jit_calls += other.jit_calls
        self.batches += other.batches
        self.input_rows += other.input_rows
        self.merges += other.merges
        self.fallbacks += other.fallbacks

    @property
    def any(self) -> bool:
        return any((self.stages, self.jit_calls, self.batches,
                    self.merges, self.fallbacks))

    def text(self) -> str:
        return (
            f"fused: {self.stages} stages, {self.batches} batches "
            f"({self.input_rows} rows) in {self.jit_calls} jit calls, "
            f"{self.compiles} compiles / {self.cache_hits} cache hits, "
            f"{self.merges} seam merges, {self.fallbacks} fallbacks"
        )


@dataclass
class ResidentPlanStats:
    """Counters for whole-query GSPMD compilation (execution/plan_compiler.py):
    maximal TPU-resident plans compiled as ONE program per batch, interior
    seams (broadcast builds + the agg repartition) fused in-program, and the
    legacy re-runs taken when a plan can't hold (duplicate build keys, state
    overflow).  One instance per ResidentPlanExec; ``merge`` folds them into
    the query-level roll-up."""

    plans: int = 0             # resident plans that executed
    programs: int = 0          # distinct (program, bucket) traces compiled
    seams: int = 0             # interior exchange edges fused in-program
    batches: int = 0           # probe batches absorbed
    jit_calls: int = 0         # whole-plan program dispatches (one per batch)
    cache_hits: int = 0        # dispatches served by an existing trace
    input_rows: int = 0        # physical probe rows (padded slots included)
    merges: int = 0            # terminal seam merges (one per plan)
    code_seam_columns: int = 0  # dict-code lanes crossing an interior seam
    fallbacks: int = 0         # overflow/dup-key -> legacy re-runs
    fallback_reasons: list[str] = field(default_factory=list)

    def merge(self, other: "ResidentPlanStats") -> None:
        self.plans += other.plans
        self.programs += other.programs
        self.seams += other.seams
        self.batches += other.batches
        self.jit_calls += other.jit_calls
        self.cache_hits += other.cache_hits
        self.input_rows += other.input_rows
        self.merges += other.merges
        self.code_seam_columns += other.code_seam_columns
        self.fallbacks += other.fallbacks
        self.fallback_reasons.extend(other.fallback_reasons)

    @property
    def launches_per_batch(self) -> float:
        return self.jit_calls / self.batches if self.batches else 0.0

    @property
    def any(self) -> bool:
        return any((self.plans, self.batches, self.jit_calls,
                    self.merges, self.fallbacks))

    def text(self) -> str:
        why = f" ({', '.join(self.fallback_reasons)})" \
            if self.fallback_reasons else ""
        return (
            f"resident: {self.plans} plans ({self.seams} seams fused), "
            f"{self.batches} batches ({self.input_rows} rows) in "
            f"{self.jit_calls} jit calls "
            f"({self.launches_per_batch:.2f} launches/batch), "
            f"{self.programs} programs / {self.cache_hits} cache hits, "
            f"{self.code_seam_columns} code-seam columns, "
            f"{self.merges} merges, {self.fallbacks} fallbacks{why}"
        )


@dataclass
class AdaptiveStats:
    """Counters + decision tags for the adaptive execution plane
    (execution/adaptive.py): phased stage activations and the join-
    distribution / skew decisions taken at activation barriers.  The
    ``decisions`` list carries compact human-readable tags
    (``flip_to_broadcast[f3]``, ``skew_split[f5:k2]``, ``keep[f3]``) that
    surface verbatim in EXPLAIN ANALYZE and system.runtime.queries."""

    activations: int = 0       # stages activated by the phased scheduler
    decision_points: int = 0   # barriers where a decision was evaluated
    broadcast_flips: int = 0   # PARTITIONED -> REPLICATED rewrites
    partition_flips: int = 0   # REPLICATED -> PARTITIONED rewrites
    skew_splits: int = 0       # heavy keys split across probe tasks
    memo_hits: int = 0         # decisions replayed from the runtime memo
    decisions: list[str] = field(default_factory=list)

    def merge(self, other: "AdaptiveStats") -> None:
        self.activations += other.activations
        self.decision_points += other.decision_points
        self.broadcast_flips += other.broadcast_flips
        self.partition_flips += other.partition_flips
        self.skew_splits += other.skew_splits
        self.memo_hits += other.memo_hits
        self.decisions.extend(other.decisions)

    @property
    def any(self) -> bool:
        return any((self.activations, self.decision_points,
                    self.broadcast_flips, self.partition_flips,
                    self.skew_splits))

    def text(self) -> str:
        tags = ", ".join(self.decisions) if self.decisions else "none"
        return (
            f"adaptive: {self.activations} phased activations, "
            f"{self.decision_points} decision points "
            f"({self.broadcast_flips} -> broadcast, "
            f"{self.partition_flips} -> partitioned, "
            f"{self.skew_splits} skew splits, "
            f"{self.memo_hits} memo hits); decisions: {tags}"
        )


@dataclass
class EncodingStats:
    """Counters for compressed execution (TRINO_TPU_ENCODED_EXEC): batches
    by encoding, bytes saved vs a flat representation, lazy columns that
    were filtered away before their thunk ever ran, and dictionary codes
    surviving exchanges.  One instance per encoding-aware operator;
    ``merge`` folds them into the query-level roll-up."""

    rle_batches: int = 0        # batches carrying >=1 RLE column
    dict_batches: int = 0       # batches carrying >=1 dictionary column
    lazy_columns: int = 0       # LAZY columns created by staging
    lazy_materialized: int = 0  # thunks that actually ran
    bytes_saved: int = 0        # flat-equivalent minus encoded bytes
    lazy_skipped_bytes: int = 0  # payload bytes never staged
    rle_agg_rows: int = 0       # rows aggregated as value * run_count
    code_group_batches: int = 0  # group-bys that ran on int32 codes
    code_join_batches: int = 0   # joins probed in code space
    exchange_code_pages: int = 0  # pages whose codes crossed a shuffle

    def merge(self, other: "EncodingStats") -> None:
        self.rle_batches += other.rle_batches
        self.dict_batches += other.dict_batches
        self.lazy_columns += other.lazy_columns
        self.lazy_materialized += other.lazy_materialized
        self.bytes_saved += other.bytes_saved
        self.lazy_skipped_bytes += other.lazy_skipped_bytes
        self.rle_agg_rows += other.rle_agg_rows
        self.code_group_batches += other.code_group_batches
        self.code_join_batches += other.code_join_batches
        self.exchange_code_pages += other.exchange_code_pages

    @property
    def any(self) -> bool:
        return any((self.rle_batches, self.dict_batches, self.lazy_columns,
                    self.bytes_saved, self.lazy_skipped_bytes,
                    self.rle_agg_rows, self.code_group_batches,
                    self.code_join_batches, self.exchange_code_pages))

    def text(self) -> str:
        never = self.lazy_columns - self.lazy_materialized
        return (
            f"encoding: {self.rle_batches} RLE / {self.dict_batches} dict "
            f"batches, {self.lazy_columns} lazy columns "
            f"({never} never materialized, "
            f"{self.lazy_skipped_bytes / 1e6:.2f} MB skipped), "
            f"{self.bytes_saved / 1e6:.2f} MB saved vs flat, "
            f"{self.rle_agg_rows} RLE-agg rows, "
            f"{self.code_group_batches} code group-bys / "
            f"{self.code_join_batches} code joins, "
            f"{self.exchange_code_pages} code pages through exchange"
        )


@dataclass
class OperatorStats:
    name: str
    input_rows: int = 0
    output_rows: int = 0
    input_batches: int = 0
    output_batches: int = 0
    wall_s: float = 0.0


@dataclass
class PipelineStats:
    operators: list[OperatorStats] = field(default_factory=list)


@dataclass
class QueryStats:
    """One query's (or one task's) operator stats, per pipeline."""

    label: str = ""
    pipelines: list[PipelineStats] = field(default_factory=list)
    scan: ScanIngestStats | None = None
    sync: "object | None" = None  # syncguard.SyncStats delta for this query
    resilience: ResilienceStats | None = None  # retry/heartbeat delta
    fused: FusedStageStats | None = None  # whole-stage compilation counters
    resident: ResidentPlanStats | None = None  # whole-plan compilation counters
    adaptive: AdaptiveStats | None = None  # adaptive-execution decisions
    encoding: EncodingStats | None = None  # compressed-execution counters

    def merge_scan(self, ingest: ScanIngestStats) -> None:
        if self.scan is None:
            self.scan = ScanIngestStats()
        self.scan.merge(ingest)

    def merge_encoding(self, enc: EncodingStats) -> None:
        if self.encoding is None:
            self.encoding = EncodingStats()
        self.encoding.merge(enc)

    def merge_fused(self, fused: FusedStageStats) -> None:
        if self.fused is None:
            self.fused = FusedStageStats()
        self.fused.merge(fused)

    def merge_resident(self, resident: ResidentPlanStats) -> None:
        if self.resident is None:
            self.resident = ResidentPlanStats()
        self.resident.merge(resident)

    def merge_sync(self, sync) -> None:
        if self.sync is None:
            from .syncguard import SyncStats

            self.sync = SyncStats()
        self.sync.merge(sync)

    def text(self) -> str:
        lines = []
        if self.label:
            lines.append(self.label)
        if self.scan is not None and self.scan.scan_batches:
            lines.append("  " + self.scan.text())
        if self.sync is not None and self.sync.host_syncs:
            lines.append("  " + self.sync.text())
        if self.resilience is not None and self.resilience.any:
            lines.append("  " + self.resilience.text())
        if self.fused is not None and self.fused.any:
            lines.append("  " + self.fused.text())
        if self.resident is not None and self.resident.any:
            lines.append("  " + self.resident.text())
        if self.adaptive is not None and self.adaptive.any:
            lines.append("  " + self.adaptive.text())
        if self.encoding is not None and self.encoding.any:
            lines.append("  " + self.encoding.text())
        for i, p in enumerate(self.pipelines):
            lines.append(f"  pipeline {i}:")
            for op in p.operators:
                lines.append(
                    f"    {op.name}: {op.wall_s * 1e3:.1f} ms, "
                    f"in {op.input_rows} rows/{op.input_batches} batches, "
                    f"out {op.output_rows} rows/{op.output_batches} batches")
        return "\n".join(lines)
