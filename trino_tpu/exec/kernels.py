"""Jitted relational kernels: grouped aggregation, join, sort, partition.

These are the TPU-native replacements for Trino's hand-specialized flat-memory
data structures (reference: operator/FlatHash.java:42, operator/join/
PagesHash.java, sql/gen/OrderingCompiler.java:70, operator/output/
PagePartitioner.java:55).  Design rules:

- **No open-addressing hash tables.**  Scatter-with-probing is hostile to the
  TPU's vector units; instead, grouping and join build both go through a
  *sort*: XLA lowers ``sort`` to an efficient on-chip bitonic network, and
  everything downstream (segment reduction, binary-search probe) is dense
  vector work on the MXU/VPU.
- **Static shapes via bucketing.**  Data-dependent sizes (group counts, join
  fan-out) are synced to host once per kernel invocation and rounded up to a
  power of two; jitted programs are cached per (spec, shape-bucket), so
  repeated batches hit the compile cache.
- **(data, valid) pairs everywhere** — same convention as ops/expr.py.

Null semantics baked in: GROUP BY treats NULL as a regular group (SQL
spec / Trino GroupByHash behavior); equi-join keys never match on NULL.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import ops as _ops  # noqa: F401  (enables jax x64 lanes)

__all__ = [
    "bucket",
    "group_ids",
    "grouped_reduce",
    "sort_perm",
    "build_join_table",
    "probe_join_table",
    "hash_combine",
    "partition_assignments",
]


def bucket(n: int, minimum: int = 8) -> int:
    """Round up to a power of two (static-shape recompile bucket)."""
    c = minimum
    while c < n:
        c <<= 1
    return c


def _canon_float(x):
    """Canonicalize float keys so hashing/grouping agree with SQL equality:
    -0.0 -> +0.0 (they compare equal but have different bits) and every NaN
    to the one canonical quiet-NaN pattern (NaN is a single GROUP BY value —
    Trino treats NaN as equal to itself for grouping/joining).  The positive
    canonical NaN also keeps all NaNs adjacent under XLA's total-order sort
    (-NaN sorts first, +NaN last)."""
    x = jnp.where(x == 0, jnp.zeros((), x.dtype), x)
    return jnp.where(jnp.isnan(x), jnp.full((), jnp.nan, x.dtype), x)


def _neq(a, b):
    """Elementwise 'different group key' compare: IEEE != except that NaN
    equals NaN (SQL grouping semantics)."""
    r = a != b
    if np.dtype(a.dtype).kind == "f":
        r = r & ~(jnp.isnan(a) & jnp.isnan(b))
    return r


# ---------------------------------------------------------------------------
# grouped aggregation: sort -> boundary-detect -> segment reduce


@lru_cache(maxsize=None)
def _group_ids_fn(num_keys: int, has_valid: tuple[bool, ...], has_live: bool):
    n_valid = sum(has_valid)

    @jax.jit
    def fn(*flat):
        datas = list(flat[:num_keys])
        valids = list(flat[num_keys:num_keys + n_valid])
        live = flat[num_keys + n_valid] if has_live else None
        # normalize: NULL lanes carry arbitrary fill (e.g. div-by-zero output);
        # zero them so every NULL is bit-identical and sorts into one run
        vmap = {}
        vi = 0
        for i in range(num_keys):
            if np.dtype(datas[i].dtype).kind == "f":
                # keys stay float (64-bit bitcasts don't survive the TPU x64
                # rewrite); canonicalization makes NaNs sort adjacent and the
                # NaN-aware boundary compare below makes them one group
                datas[i] = _canon_float(datas[i])
            if has_valid[i]:
                v = valids[vi]
                vi += 1
                datas[i] = jnp.where(v, datas[i], jnp.zeros((), datas[i].dtype))
                vmap[i] = v
        # lexsort: last key in the tuple is the primary sort key; dead rows
        # (selection-mask filtering) sort after every live row
        sort_keys = []
        for i in reversed(range(num_keys)):
            sort_keys.append(datas[i])
            if i in vmap:
                sort_keys.append(vmap[i])
        if live is not None:
            sort_keys.append(~live)
        perm = jnp.lexsort(tuple(sort_keys))
        new_group = jnp.zeros(datas[0].shape, dtype=jnp.bool_)
        for i in range(num_keys):
            d = datas[i][perm]
            diff = jnp.concatenate([jnp.ones((1,), jnp.bool_), _neq(d[1:], d[:-1])])
            if i in vmap:
                v = vmap[i][perm]
                diff = diff | jnp.concatenate(
                    [jnp.ones((1,), jnp.bool_), v[1:] != v[:-1]]
                )
            new_group = new_group | diff
        if live is not None:
            lv = live[perm]
            # force a boundary at the live->dead transition so dead rows can
            # never extend the last live group, and count live groups only;
            # dead rows get gids >= num_groups and fall out of every scatter
            new_group = new_group | jnp.concatenate(
                [jnp.ones((1,), jnp.bool_), lv[1:] != lv[:-1]])
            gid = jnp.cumsum(new_group.astype(jnp.int32)) - 1
            return perm, gid, jnp.sum(new_group & lv)
        gid = jnp.cumsum(new_group.astype(jnp.int32)) - 1
        return perm, gid, gid[-1] + 1

    return fn


def group_ids(keys: Sequence[tuple], live=None) -> tuple:
    """keys: [(data, valid|None), ...] equal-length 1-D arrays; ``live`` an
    optional row mask (False = dead padded/filtered row).

    Returns (perm, gid, num_groups): ``perm`` sorts rows so equal keys are
    adjacent (dead rows last); ``gid[i]`` is the dense group id of sorted row
    i; dead rows receive gids >= num_groups.  perm/gid stay on device."""
    num_keys = len(keys)
    has_valid = tuple(v is not None for _, v in keys)
    datas = [jnp.asarray(d) for d, _ in keys]
    valids = [jnp.asarray(v) for _, v in keys if v is not None]
    extra = [jnp.asarray(live)] if live is not None else []
    perm, gid, n = _group_ids_fn(num_keys, has_valid, live is not None)(
        *datas, *valids, *extra)
    return perm, gid, int(n)


_SENTINELS = {
    "min": {
        "i": lambda dt: jnp.iinfo(dt).max,
        "f": lambda dt: jnp.inf,
        "b": lambda dt: True,
    },
    "max": {
        "i": lambda dt: jnp.iinfo(dt).min,
        "f": lambda dt: -jnp.inf,
        "b": lambda dt: False,
    },
}


def _sentinel(fn: str, dtype) -> object:
    kind = np.dtype(dtype).kind
    k = "f" if kind == "f" else ("b" if kind == "b" else "i")
    return _SENTINELS[fn][k](dtype)


@lru_cache(maxsize=None)
def _reduce_fn(spec: tuple, cap: int):
    """spec: tuple of (fn, has_valid, dtype_str, distinct) per aggregate;
    inputs to the jitted fn: perm, gid, then per-agg (data [, valid])."""

    @jax.jit
    def fn(perm, gid, *flat):
        outs = []
        i = 0
        ones = jnp.ones(perm.shape, dtype=jnp.int64)
        for fname, has_valid, dtype_str, distinct in spec:
            dtype = jnp.dtype(dtype_str)
            if fname == "count_star":
                c = ones
                if has_valid:  # the live mask of a padded batch
                    c = flat[i][perm].astype(jnp.int64)
                    i += 1
                outs.append((jax.ops.segment_sum(c, gid, cap), None))
                continue
            data = flat[i][perm]
            i += 1
            valid = None
            if has_valid:
                valid = flat[i][perm]
                i += 1
            if distinct:
                # rows sorted by group key only; distinct needs per-(group,
                # value) dedup: mark first occurrence within (gid, valid,
                # value) runs — validity participates so a NULL row whose
                # storage fill collides with a real value stays its own run
                if np.dtype(data.dtype).kind == "f":
                    data = _canon_float(data)  # NaN is ONE distinct value
                if valid is not None:
                    order = jnp.lexsort((data, valid, gid))
                    v2 = valid[order]
                else:
                    order = jnp.lexsort((data, gid))
                    v2 = None
                d2, g2 = data[order], gid[order]
                first = jnp.concatenate(
                    [jnp.ones((1,), jnp.bool_), _neq(d2[1:], d2[:-1]) | (g2[1:] != g2[:-1])]
                )
                if v2 is not None:
                    first = first | jnp.concatenate(
                        [jnp.ones((1,), jnp.bool_), v2[1:] != v2[:-1]])
                keep = first if v2 is None else (first & v2)
                if fname in ("count", "count_star"):
                    outs.append((jax.ops.segment_sum(keep.astype(jnp.int64), g2, cap), None))
                    continue
                if fname == "sum":
                    x = jnp.where(keep, d2, jnp.zeros((), dtype))
                    s = jax.ops.segment_sum(x.astype(dtype), g2, cap)
                    anyv = jax.ops.segment_max(keep, g2, cap)
                    outs.append((s, anyv))
                    continue
                raise NotImplementedError(f"distinct {fname}")
            if fname == "count":
                c = ones if valid is None else valid.astype(jnp.int64)
                outs.append((jax.ops.segment_sum(c, gid, cap), None))
            elif fname == "sum":
                x = data if valid is None else jnp.where(valid, data, jnp.zeros((), data.dtype))
                s = jax.ops.segment_sum(x.astype(dtype), gid, cap)
                anyv = (
                    None
                    if valid is None
                    else jax.ops.segment_max(valid, gid, cap)
                )
                outs.append((s, anyv))
            elif fname in ("min", "max"):
                sent = _sentinel(fname, data.dtype)
                x = data if valid is None else jnp.where(valid, data, sent)
                red = jax.ops.segment_min if fname == "min" else jax.ops.segment_max
                r = red(x, gid, cap)
                anyv = (
                    None
                    if valid is None
                    else jax.ops.segment_max(valid, gid, cap)
                )
                outs.append((r, anyv))
            elif fname == "any_value":
                # scatter only VALID rows (NULL lanes carry storage fill)
                tgt = gid if valid is None else jnp.where(valid, gid, cap)
                r = jnp.zeros((cap + 1,), data.dtype).at[tgt].set(data)[:cap]
                anyv = (
                    None
                    if valid is None
                    else jnp.zeros((cap,), jnp.bool_).at[gid].max(valid)
                )
                outs.append((r, anyv))
            else:
                raise NotImplementedError(f"aggregate {fname}")
        return outs

    return fn


_PALLAS_STATE = {"enabled": None}


def _pallas_enabled() -> bool:
    import os

    if _PALLAS_STATE["enabled"] is None:
        mode = os.environ.get("TRINO_TPU_PALLAS", "1")
        if mode == "0":
            _PALLAS_STATE["enabled"] = False
        else:
            from ..ops.pallas_kernels import pallas_available

            # compiled kernels only beat XLA on real TPU lanes; interpret
            # mode is for tests (force with TRINO_TPU_PALLAS=force)
            _PALLAS_STATE["enabled"] = pallas_available() and (
                mode == "force" or jax.default_backend() == "tpu")
    return _PALLAS_STATE["enabled"]


def _pallas_f32_sum(perm, gid, cap: int, data, valid):
    """REAL-sum fast path: blockwise VMEM accumulation instead of XLA's
    scatter segment_sum (ops/pallas_kernels.py).  Returns (sums, anyvalid)
    or None when pallas fails (flag flips off, XLA takes over)."""
    from ..ops import pallas_kernels as PK

    try:
        interpret = jax.default_backend() != "tpu"
        vals = jnp.asarray(data)[perm]
        lv = None if valid is None else jnp.asarray(valid)[perm]
        s = PK.masked_segment_sum_f32(vals, gid, lv, cap, interpret=interpret)
        anyv = None
        if valid is not None:  # the validity bit is one cheap segment_max
            anyv = jax.ops.segment_max(lv, gid, cap)
        return s, anyv
    except Exception:  # noqa: BLE001 — pallas unavailable: permanent fallback
        _PALLAS_STATE["enabled"] = False
        return None


def grouped_reduce(
    perm,
    gid,
    num_groups: int,
    aggs: Sequence[tuple],
) -> list[tuple[np.ndarray, Optional[np.ndarray]]]:
    """aggs: [(fn, data|None, valid|None, out_dtype, distinct), ...].

    Returns per-agg (values, valid|None) arrays of length num_groups.
    """
    cap = bucket(num_groups)
    results: list = [None] * len(aggs)
    spec = []
    flat = []
    xla_slots = []
    for idx, (fn, data, valid, dtype, distinct) in enumerate(aggs):
        if (fn == "sum" and data is not None and not distinct
                and np.dtype(dtype) == np.float32 and cap <= 64
                and _pallas_enabled()):
            out = _pallas_f32_sum(jnp.asarray(perm), jnp.asarray(gid), cap,
                                  data, valid)
            if out is not None:
                results[idx] = (out[0][:num_groups],
                                None if out[1] is None
                                else out[1][:num_groups])
                continue
        if fn == "count_star" or data is None:
            spec.append(("count_star", valid is not None, "int64", False))
            if valid is not None:  # live mask: count only live rows
                flat.append(jnp.asarray(valid))
            xla_slots.append(idx)
            continue
        spec.append((fn, valid is not None, np.dtype(dtype).str, bool(distinct)))
        flat.append(jnp.asarray(data))
        if valid is not None:
            flat.append(jnp.asarray(valid))
        xla_slots.append(idx)
    if spec:
        outs = _reduce_fn(tuple(spec), cap)(
            jnp.asarray(perm), jnp.asarray(gid), *flat)
        for idx, (data, valid) in zip(xla_slots, outs):
            results[idx] = (data[:num_groups],
                            None if valid is None else valid[:num_groups])
    return results


def group_keys_out(perm, gid, num_groups: int, keys: Sequence[tuple]):
    """Materialize one representative key row per group (device arrays out;
    dead rows carry gids >= cap-scatter range and are dropped)."""
    cap = bucket(num_groups)
    out = []
    gid_j = jnp.asarray(gid)
    perm_j = jnp.asarray(perm)
    for data, valid in keys:
        d = jnp.zeros((cap,), jnp.asarray(data).dtype).at[gid_j].set(
            jnp.asarray(data)[perm_j], mode="drop")
        out_d = d[:num_groups]
        if valid is not None:
            v = jnp.zeros((cap,), jnp.bool_).at[gid_j].max(
                jnp.asarray(valid)[perm_j], mode="drop")
            out.append((out_d, v[:num_groups]))
        else:
            out.append((out_d, None))
    return out


# ---------------------------------------------------------------------------
# sort


def sort_perm(keys: Sequence[tuple]) -> np.ndarray:
    """keys: [(data, valid|None, ascending, nulls_first), ...] in major-to-
    minor significance order.  Returns the stable sorting permutation.

    Implemented as a single ``jnp.lexsort`` (XLA variadic sort)."""
    sort_cols = []
    for data, valid, ascending, nulls_first in reversed(list(keys)):
        d = jnp.asarray(data)
        kind = np.dtype(d.dtype).kind
        if not ascending:
            if kind == "b":
                d = ~d
            elif kind == "f":
                d = -d.astype(jnp.float64)
            else:
                # bitwise NOT is a bijective order reversal; unary minus maps
                # INT64_MIN to itself under two's-complement wraparound
                d = ~d.astype(jnp.int64)
        if valid is not None:
            # canonicalize NULL rows' payload FIRST (before NaN ranking):
            # two NULLs must tie exactly on every derived column, or their
            # garbage data would decide the less-significant keys
            v = jnp.asarray(valid)
            d = jnp.where(v, d, jnp.zeros((), d.dtype))
        nan_rank = None
        if kind == "f":
            # NaN sorts largest (Trino convention) via its own rank column —
            # mapping NaN into the value domain (+/-inf) would tie with real
            # infinities; the rank is more significant than the value
            nan = jnp.isnan(d)
            nan_rank = jnp.where(nan, 1 if ascending else 0,
                                 0 if ascending else 1)
            d = jnp.where(nan, jnp.zeros((), d.dtype), d)
        sort_cols.append(d)
        if nan_rank is not None:
            sort_cols.append(nan_rank)
        if valid is not None:
            # secondary column is sorted after; null rank must be primary
            null_rank = jnp.where(v, 1, 0) if nulls_first else jnp.where(v, 0, 1)
            sort_cols.append(null_rank)
    perm = jnp.lexsort(tuple(sort_cols))
    return np.asarray(perm)


# ---------------------------------------------------------------------------
# join: sorted-build + binary-search probe

_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)


def _mix64(h):
    h = (h ^ (h >> 30)) * jnp.uint64(_M1)
    h = (h ^ (h >> 27)) * jnp.uint64(_M2)
    return h ^ (h >> 31)


def _f64_hash_word(a):
    """Full-entropy uint64 hash word for a canonical float64 column, built
    arithmetically — the TPU x64 rewrite cannot compile any 64-bit bitcast
    (f64->u64, f64->2xu32 and frexp all fail).  The value is range-reduced
    into an f32-friendly window by a log2-derived class, then split into
    three float32 words whose cascade captures the whole 53-bit significand
    (24*3 > 53), so equal doubles hash equal and distinct doubles collide
    with negligible probability across the full f64 range."""
    fin = jnp.isfinite(a)
    mag = jnp.abs(a)
    safe_mag = jnp.where(mag > 0, mag, 1.0)
    cls = jnp.clip(jnp.floor(jnp.log2(safe_mag) / 120.0), -9.0, 9.0)
    s = 2.0 ** (-60.0 * cls)  # applied twice; 2**(-120*cls) would overflow
    scaled = jnp.where(fin, a * s * s, 0.0)
    w1 = scaled.astype(jnp.float32)
    r1 = scaled - w1.astype(jnp.float64)
    w2 = r1.astype(jnp.float32)
    r2 = r1 - w2.astype(jnp.float64)
    w3 = r2.astype(jnp.float32)
    tag = jnp.where(jnp.isnan(a), 3, jnp.where(a == jnp.inf, 1,
                    jnp.where(a == -jnp.inf, 2, 0)))
    meta = (cls.astype(jnp.int32) + 16) | (tag.astype(jnp.int32) << 8)

    def u32(w):
        return jax.lax.bitcast_convert_type(w, jnp.uint32).astype(jnp.uint64)

    lo = u32(w1) | (u32(w2) << 32)
    hi = u32(w3) | (meta.astype(jnp.uint32).astype(jnp.uint64) << 32)
    return _mix64(lo) ^ hi


def hash_combine(datas: Sequence) -> jnp.ndarray:
    """Combine n key columns into one uint64 hash lane (splitmix64 mix).

    Used for candidate equality (verified exactly afterwards) and for
    partition assignment (no verification needed)."""
    h = jnp.zeros(jnp.asarray(datas[0]).shape, dtype=jnp.uint64)
    for d in datas:
        x = jnp.asarray(d)
        if x.dtype == jnp.bool_:
            x = x.astype(jnp.uint64)
        elif np.dtype(x.dtype).kind == "f":
            x = _f64_hash_word(_canon_float(x.astype(jnp.float64)))
        else:
            x = x.astype(jnp.int64).astype(jnp.uint64)
        h = _mix64(h ^ (x + jnp.uint64(0x9E3779B97F4A7C15)))
    return h


@jax.jit
def _sorted_hash(h):
    perm = jnp.argsort(h)
    return h[perm], perm


class JoinTable:
    """Sorted-hash build side (the PagesHash/LookupSource equivalent)."""

    __slots__ = ("sorted_hash", "perm", "key_datas", "has_null_key", "num_rows")

    def __init__(self, sorted_hash, perm, key_datas, has_null_key, num_rows):
        self.sorted_hash = sorted_hash
        self.perm = perm  # build row index per sorted-hash position
        self.key_datas = key_datas  # original (unsorted) key arrays for verify
        self.has_null_key = has_null_key
        self.num_rows = num_rows


def build_join_table(keys: Sequence[tuple], num_rows: Optional[int] = None) -> JoinTable:
    """keys: [(data, valid|None), ...] over build rows.  Rows with any NULL
    key never match (SQL equi-join) — they are excluded via a reserved hash.

    Empty ``keys`` (with explicit ``num_rows``) builds a cross-join table:
    every probe row matches every build row (nested-loop fallback, mirrors
    operator/join/NestedLoopJoinOperator.java:45)."""
    if not keys:
        return JoinTable(None, None, [], False, int(num_rows or 0))
    datas = [jnp.asarray(d) for d, _ in keys]
    n = int(datas[0].shape[0]) if datas else 0
    h = hash_combine(datas)
    null_mask = None
    for _, v in keys:
        if v is not None:
            nm = ~jnp.asarray(v)
            null_mask = nm if null_mask is None else (null_mask | nm)
    has_null = False
    if null_mask is not None:
        has_null = bool(np.asarray(jnp.any(null_mask)))
        # reserved sentinel: max uint64 never produced for probes (probes with
        # null keys are masked out before lookup)
        h = jnp.where(null_mask, jnp.uint64(0xFFFFFFFFFFFFFFFF), h)
    sh, perm = _sorted_hash(h)
    return JoinTable(sh, perm, datas, has_null, n)


@lru_cache(maxsize=None)
def _probe_ranges_fn():
    @jax.jit
    def fn(sorted_hash, probe_hash):
        lo = jnp.searchsorted(sorted_hash, probe_hash, side="left")
        hi = jnp.searchsorted(sorted_hash, probe_hash, side="right")
        return lo, hi - lo

    return fn


@lru_cache(maxsize=None)
def _expand_fn(cap: int):
    """Expansion kernel sized to a power-of-two bucket ``cap`` >= total so
    varying per-batch match counts reuse a handful of compiled programs;
    slots >= total produce clamped garbage the caller slices off."""

    @jax.jit
    def fn(lo, counts, perm):
        n = counts.shape[0]
        ends = jnp.cumsum(counts)
        starts = ends - counts
        slot = jnp.arange(cap)
        probe_id = jnp.clip(jnp.searchsorted(ends, slot, side="right"), 0, n - 1)
        within = slot - starts[probe_id]
        build_pos = lo[probe_id] + within
        return probe_id, perm[jnp.clip(build_pos, 0, perm.shape[0] - 1)]

    return fn


def probe_join_table(
    table: JoinTable, probe_keys: Sequence[tuple], live=None
) -> tuple[np.ndarray, np.ndarray]:
    """Returns (probe_idx, build_idx) pairs of ALL equi-matches, exactly
    verified.  Caller layers inner/left/semi semantics on top.  ``live``
    masks padded/filtered-out probe rows (they never match).

    ``n_probe`` must be passed for the keyless (cross-join) table."""
    if not table.key_datas:  # cross join
        nb = table.num_rows
        n_probe = probe_keys  # caller passes the row count in place of keys
        assert isinstance(n_probe, int), "cross-join probe needs a row count"
        return (np.repeat(np.arange(n_probe, dtype=np.int64), nb),
                np.tile(np.arange(nb, dtype=np.int64), n_probe))
    pdatas = [jnp.asarray(d) for d, _ in probe_keys]
    n_probe = int(pdatas[0].shape[0])
    if n_probe == 0 or table.num_rows == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    ph = hash_combine(pdatas)
    pnull = None
    for _, v in probe_keys:
        if v is not None:
            nm = ~jnp.asarray(v)
            pnull = nm if pnull is None else (pnull | nm)
    if pnull is not None:
        # flip to a hash that cannot exist in the table's non-null region
        ph = jnp.where(pnull, jnp.uint64(0xFFFFFFFFFFFFFFFE), ph)
    lo, counts = _probe_ranges_fn()(table.sorted_hash, ph)
    if pnull is not None:
        counts = jnp.where(pnull, 0, counts)
    if live is not None:
        counts = jnp.where(jnp.asarray(live), counts, 0)
    if table.has_null_key:
        # sentinel region must never match
        counts = jnp.where(ph == jnp.uint64(0xFFFFFFFFFFFFFFFF), 0, counts)
    total = int(np.asarray(jnp.sum(counts)))
    if total == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    probe_id, build_id = _expand_fn(bucket(total))(lo, counts, table.perm)
    probe_id, build_id = probe_id[:total], build_id[:total]
    # exact verification (hash candidates -> equality on every key column);
    # float equality mirrors the grouping semantics: NaN matches NaN
    ok = jnp.ones((total,), jnp.bool_)
    for (pd, pv), bd in zip(probe_keys, table.key_datas):
        p, b = jnp.asarray(pd)[probe_id], bd[build_id]
        ok = ok & ~_neq(p, b)
    # one device->host round trip for all three arrays (not three)
    keep, probe_id, build_id = jax.device_get((ok, probe_id, build_id))
    return probe_id[keep], build_id[keep]


# ---------------------------------------------------------------------------
# partitioning (shuffle producer — PagePartitioner.partitionPage equivalent)


def partition_assignments(keys: Sequence[tuple], num_partitions: int) -> np.ndarray:
    """Row -> partition id by key hash (NULL keys -> partition 0)."""
    datas = [jnp.asarray(d) for d, _ in keys]
    h = hash_combine(datas)
    null_mask = None
    for _, v in keys:
        if v is not None:
            nm = ~jnp.asarray(v)
            null_mask = nm if null_mask is None else (null_mask | nm)
    part = (h % jnp.uint64(num_partitions)).astype(jnp.int32)
    if null_mask is not None:
        part = jnp.where(null_mask, 0, part)
    return np.asarray(part)
