"""Jitted relational kernels: grouped aggregation, join, sort, partition.

These are the TPU-native replacements for Trino's hand-specialized flat-memory
data structures (reference: operator/FlatHash.java:42, operator/join/
PagesHash.java, sql/gen/OrderingCompiler.java:70, operator/output/
PagePartitioner.java:55).  Design rules:

- **Sort-first, hash as the measured alternative.**  Grouping and join build
  default to a *sort*: XLA lowers ``sort`` to an efficient on-chip bitonic
  network, and everything downstream (segment reduction, binary-search
  probe) is dense vector work on the MXU/VPU.  ``TRINO_TPU_HASH_IMPL``
  selects a second, open-addressing implementation of the same contracts
  (Pallas linear-probing kernels, ops/pallas_kernels.py) so the two can be
  baked off per NDV (bench.py --ndv) instead of argued about.
- **Static shapes via bucketing.**  Data-dependent sizes (group counts, join
  fan-out) are synced to host once per kernel invocation and rounded up to a
  power of two; jitted programs are cached per (spec, shape-bucket), so
  repeated batches hit the compile cache.
- **(data, valid) pairs everywhere** — same convention as ops/expr.py.

Null semantics baked in: GROUP BY treats NULL as a regular group (SQL
spec / Trino GroupByHash behavior); equi-join keys never match on NULL.
"""

from __future__ import annotations

import os
from ..caching.executable_cache import jit_memo
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import ops as _ops  # noqa: F401  (enables jax x64 lanes)
from ..spi.errors import GENERIC_INTERNAL_ERROR, TrinoError

__all__ = [
    "bucket",
    "group_ids",
    "group_ids_auto",
    "hash_group_ids",
    "hash_impl",
    "key_planes",
    "grouped_reduce",
    "sort_perm",
    "build_join_table",
    "probe_join_table",
    "hash_combine",
    "partition_assignments",
    "rle_fill",
]


def bucket(n: int, minimum: int = 8) -> int:
    """Round up to a power of two (static-shape recompile bucket)."""
    c = minimum
    while c < n:
        c <<= 1
    return c


def rle_fill(value, length: int):
    """Expand an RLE run on device: ``jnp.full`` materializes the run from
    ONE host scalar, so no run-length payload ever crosses the host/device
    boundary (the expand-at-the-last-moment half of compressed execution)."""
    value = np.asarray(value)
    return jnp.full(length, value, dtype=value.dtype)


@jit_memo("kernels._searchsorted_method")
def _searchsorted_method(shape: tuple) -> str:
    n_needles = 1
    for s in shape:
        n_needles *= int(s)
    return "sort" if n_needles >= 4096 else "scan"


def searchsorted(a, v, side: str = "left"):
    """TPU-aware searchsorted: the default 'scan' method is a serial
    binary search — log(n) dependent HBM gathers PER NEEDLE — measured at
    ~1s for 2M needles on v5e, while the 'sort' method (sort the concat,
    derive positions) rides the optimized XLA bitonic sort at ~1ms.  Small
    needle counts keep 'scan' (sorting the haystack for 8 needles wastes a
    full pass).  The method pick is memoized per needle SHAPE: this runs on
    every trace of every jitted program, so the per-call product over the
    dims is hoisted into a registry memo keyed like the jit cache itself."""
    method = (_searchsorted_method(tuple(v.shape))
              if hasattr(v, "shape") else "scan")
    return jnp.searchsorted(a, v, side=side, method=method)


def _canon_float(x):
    """Canonicalize float keys so hashing/grouping agree with SQL equality:
    -0.0 -> +0.0 (they compare equal but have different bits) and every NaN
    to the one canonical quiet-NaN pattern (NaN is a single GROUP BY value —
    Trino treats NaN as equal to itself for grouping/joining).  The positive
    canonical NaN also keeps all NaNs adjacent under XLA's total-order sort
    (-NaN sorts first, +NaN last)."""
    x = jnp.where(x == 0, jnp.zeros((), x.dtype), x)
    return jnp.where(jnp.isnan(x), jnp.full((), jnp.nan, x.dtype), x)


def _neq(a, b):
    """Elementwise 'different group key' compare: IEEE != except that NaN
    equals NaN (SQL grouping semantics)."""
    r = a != b
    if np.dtype(a.dtype).kind == "f":
        r = r & ~(jnp.isnan(a) & jnp.isnan(b))
    return r


# ---------------------------------------------------------------------------
# grouped aggregation: sort -> boundary-detect -> segment reduce


@jit_memo("kernels._group_ids_fn")
def _group_ids_fn(num_keys: int, has_valid: tuple[bool, ...], has_live: bool):
    n_valid = sum(has_valid)

    @jax.jit
    def fn(*flat):
        datas = list(flat[:num_keys])
        valids = list(flat[num_keys:num_keys + n_valid])
        live = flat[num_keys + n_valid] if has_live else None
        # normalize: NULL lanes carry arbitrary fill (e.g. div-by-zero output);
        # zero them so every NULL is bit-identical and sorts into one run
        vmap = {}
        vi = 0
        for i in range(num_keys):
            if np.dtype(datas[i].dtype).kind == "f":
                # keys stay float (64-bit bitcasts don't survive the TPU x64
                # rewrite); canonicalization makes NaNs sort adjacent and the
                # NaN-aware boundary compare below makes them one group
                datas[i] = _canon_float(datas[i])
            if has_valid[i]:
                v = valids[vi]
                vi += 1
                datas[i] = jnp.where(v, datas[i], jnp.zeros((), datas[i].dtype))
                vmap[i] = v
        # lexsort: last key in the tuple is the primary sort key; dead rows
        # (selection-mask filtering) sort after every live row
        sort_keys = []
        for i in reversed(range(num_keys)):
            sort_keys.append(datas[i])
            if i in vmap:
                sort_keys.append(vmap[i])
        if live is not None:
            sort_keys.append(~live)
        perm = jnp.lexsort(tuple(sort_keys))
        new_group = jnp.zeros(datas[0].shape, dtype=jnp.bool_)
        for i in range(num_keys):
            d = datas[i][perm]
            diff = jnp.concatenate([jnp.ones((1,), jnp.bool_), _neq(d[1:], d[:-1])])
            if i in vmap:
                v = vmap[i][perm]
                diff = diff | jnp.concatenate(
                    [jnp.ones((1,), jnp.bool_), v[1:] != v[:-1]]
                )
            new_group = new_group | diff
        if live is not None:
            lv = live[perm]
            # force a boundary at the live->dead transition so dead rows can
            # never extend the last live group, and count live groups only;
            # dead rows get gids >= num_groups and fall out of every scatter
            new_group = new_group | jnp.concatenate(
                [jnp.ones((1,), jnp.bool_), lv[1:] != lv[:-1]])
            gid = jnp.cumsum(new_group.astype(jnp.int32)) - 1
            return perm, gid, jnp.sum(new_group & lv)
        gid = jnp.cumsum(new_group.astype(jnp.int32)) - 1
        return perm, gid, gid[-1] + 1

    return fn


def group_ids(keys: Sequence[tuple], live=None) -> tuple:
    """keys: [(data, valid|None), ...] equal-length 1-D arrays; ``live`` an
    optional row mask (False = dead padded/filtered row).

    Returns (perm, gid, num_groups): ``perm`` sorts rows so equal keys are
    adjacent (dead rows last); ``gid[i]`` is the dense group id of sorted row
    i; dead rows receive gids >= num_groups.  perm/gid stay on device."""
    num_keys = len(keys)
    has_valid = tuple(v is not None for _, v in keys)
    datas = [jnp.asarray(d) for d, _ in keys]
    valids = [jnp.asarray(v) for _, v in keys if v is not None]
    extra = [jnp.asarray(live)] if live is not None else []
    perm, gid, n = _group_ids_fn(num_keys, has_valid, live is not None)(
        *datas, *valids, *extra)
    return perm, gid, int(n)


# ---------------------------------------------------------------------------
# open-addressing grouping (TRINO_TPU_HASH_IMPL): Pallas linear-probing
# insert/probe kernels as a second implementation of the group_ids contract

# compiled tables must stay VMEM-honest: (planes + gid + slack) * S * 4B
_HASH_VMEM_BUDGET = 8 << 20

_HASH_IMPL_STATE = {"failed": False}  # auto mode: permanent sort fallback


def hash_impl() -> str:
    """Resolved TRINO_TPU_HASH_IMPL knob: 'auto' (sort on CPU, pallas on TPU
    when the table fits VMEM), 'pallas' (force — interpret mode off-TPU),
    'sort' (force the lexsort path).  Read per call, not cached: tests and
    the bench flip it between legs."""
    mode = os.environ.get("TRINO_TPU_HASH_IMPL", "auto").lower()
    return mode if mode in ("pallas", "sort") else "auto"


def hash_interpret() -> bool:
    """Interpret-mode pallas (identical kernels as pure XLA) everywhere but
    a real TPU backend; TRINO_TPU_HASH_INTERPRET=1 forces it for A/B runs."""
    if os.environ.get("TRINO_TPU_HASH_INTERPRET") == "1":
        return True
    return jax.default_backend() != "tpu"


def _plane_count(keys: Sequence[tuple]) -> int:
    n = 0
    for d, v in keys:
        kind = np.dtype(jnp.asarray(d).dtype).kind
        n += 4 if kind == "f" else (1 if kind == "b" else 2)
        n += 1 if v is not None else 0
    return n


def _use_hash_impl(n_rows: int, n_planes: int) -> bool:
    mode = hash_impl()
    if mode == "sort" or not n_rows:
        return False
    from ..ops.pallas_kernels import pallas_available

    if not pallas_available():
        return False
    if mode == "pallas":
        return True
    if _HASH_IMPL_STATE["failed"] or jax.default_backend() != "tpu":
        return False
    return (n_planes + 2) * bucket(2 * n_rows) * 4 <= _HASH_VMEM_BUDGET


def _f64_key_planes(c) -> list:
    """Four uint32 planes INJECTIVE over canonical float64 values: the same
    range-reduction as _f64_hash_word (the TPU x64 rewrite compiles no
    64-bit bitcast) but keeping the w1/w2/w3 words and the class/tag meta
    word separate instead of mixing them.  scaled = w1 + w2 + w3 exactly
    (each split removes >= 24 significand bits, 24*3 > 53), and the power-
    of-two scale is exact, so equal doubles give equal planes and distinct
    doubles distinct planes: plane equality IS SQL key equality."""
    fin = jnp.isfinite(c)
    mag = jnp.abs(c)
    safe_mag = jnp.where(mag > 0, mag, 1.0)
    cls = jnp.clip(jnp.floor(jnp.log2(safe_mag) / 120.0), -9.0, 9.0)
    s = 2.0 ** (-60.0 * cls)  # applied twice; 2**(-120*cls) would overflow
    scaled = jnp.where(fin, c * s * s, 0.0)
    w1 = scaled.astype(jnp.float32)
    r1 = scaled - w1.astype(jnp.float64)
    w2 = r1.astype(jnp.float32)
    r2 = r1 - w2.astype(jnp.float64)
    w3 = r2.astype(jnp.float32)
    tag = jnp.where(jnp.isnan(c), 3, jnp.where(c == jnp.inf, 1,
                    jnp.where(c == -jnp.inf, 2, 0)))
    meta = (cls.astype(jnp.int32) + 16) | (tag.astype(jnp.int32) << 8)

    def u32(w):
        return jax.lax.bitcast_convert_type(w, jnp.uint32)

    return [u32(w1), u32(w2), u32(w3), meta.astype(jnp.uint32)]


def key_planes(keys: Sequence[tuple]) -> list:
    """Normalize key columns into uint32 planes whose elementwise equality
    is exactly SQL group-key equality: ints/bools split into lo/hi 32-bit
    words, floats canonicalized (-0 -> +0, one NaN) then decomposed into the
    injective w1/w2/w3/meta cascade, nullable keys zero their data planes
    and append a validity plane (NULL is its own group, distinct from 0)."""
    out: list = []
    for d, v in keys:
        d = jnp.asarray(d)
        kind = np.dtype(d.dtype).kind
        if kind == "f":
            kp = _f64_key_planes(_canon_float(d.astype(jnp.float64)))
        elif kind == "b":
            kp = [d.astype(jnp.uint32)]
        else:
            x = d.astype(jnp.int64)
            kp = [(x & 0xFFFFFFFF).astype(jnp.uint32),
                  ((x >> 32) & 0xFFFFFFFF).astype(jnp.uint32)]
        if v is not None:
            vv = jnp.asarray(v)
            kp = [jnp.where(vv, p, jnp.zeros((), p.dtype)) for p in kp]
            kp.append(vv.astype(jnp.uint32))
        out.extend(kp)
    return out


def hash_row_gids(keys: Sequence[tuple], live=None,
                  num_slots: Optional[int] = None):
    """Open-addressing core: per-ORIGINAL-row dense group ids in first-
    occurrence order via the Pallas insert kernel.  Returns (row_gid,
    count): dead rows get ``num_slots`` (>= any real id), ``count`` stays a
    DEVICE scalar — zero host syncs, usable inside jitted programs."""
    from ..ops import pallas_kernels as PK

    datas = [jnp.asarray(d) for d, _ in keys]
    n = int(datas[0].shape[0])
    S = int(num_slots) if num_slots else bucket(2 * max(n, 1))
    planes = key_planes(keys)
    h = hash_combine(planes)
    h32 = (h ^ (h >> jnp.uint64(32))).astype(jnp.uint32)
    lv = None if live is None else jnp.asarray(live)
    row_gid, count, _table, _sgid = PK.hash_insert(
        jnp.stack(planes), h32, lv, S, interpret=hash_interpret())
    return row_gid, count


@jit_memo("kernels._hash_finish_fn")
def _hash_finish_fn():
    @jax.jit
    def fn(row_gid):
        # jnp.argsort is stable: rows within a group keep input order, and
        # dead rows (gid = num_slots, beyond every real id) sort last
        perm = jnp.argsort(row_gid)
        return perm, row_gid[perm].astype(jnp.int32)

    return fn


def hash_group_ids(keys: Sequence[tuple], live=None) -> tuple:
    """Open-addressing alternative to :func:`group_ids` — same contract:
    (perm, gid, num_groups) with gid nondecreasing over sorted rows, equal
    keys adjacent, dead rows last with gid >= num_groups, and ONE host sync
    for the count.  Group ids come out in first-occurrence order instead of
    key order; both satisfy the documented contract, operator output is
    order-canonicalized downstream.  The expensive multi-key 64-bit lexsort
    becomes one int32 sort over the kernel-assigned ids."""
    if not keys:
        raise TrinoError(GENERIC_INTERNAL_ERROR,
                         "hash_group_ids needs at least one key")
    n = int(jnp.asarray(keys[0][0]).shape[0])
    if n == 0:
        return jnp.arange(0), jnp.zeros(0, jnp.int32), 0
    row_gid, count = hash_row_gids(keys, live)
    perm, gid = _hash_finish_fn()(row_gid)
    return perm, gid, int(count)


def group_ids_auto(keys: Sequence[tuple], live=None) -> tuple:
    """group_ids with the TRINO_TPU_HASH_IMPL knob applied.  'auto' falls
    back to sort permanently if the pallas path ever fails; an explicit
    'pallas' propagates errors (tests must not silently pass on the wrong
    implementation)."""
    n = int(jnp.asarray(keys[0][0]).shape[0]) if keys else 0
    if keys and _use_hash_impl(n, _plane_count(keys)):
        if hash_impl() == "pallas":
            return hash_group_ids(keys, live)
        try:
            return hash_group_ids(keys, live)
        except Exception:  # noqa: BLE001 — auto mode: permanent fallback
            _HASH_IMPL_STATE["failed"] = True
    return group_ids(keys, live)


SMALL_CODES_LIMIT = 4096  # max fused-code group space for the no-sort path
MASKED_AGG_LIMIT = 128  # masked-reduction aggregate path (no sort, no gather)


def _code_layout(sizes: tuple, has_valid: tuple):
    """Fused-code layout shared by the small-codes grouping paths: each key
    gets ``sizes[k]`` code slots plus one null slot when nullable; the fused
    group id is sum(code_k * strides[k]) in [0, total)."""
    slots = tuple(s + 1 if hv else s for s, hv in zip(sizes, has_valid))
    total = 1
    for s in slots:
        total *= s
    strides = []
    acc = 1
    for s in reversed(slots):
        strides.append(acc)
        acc *= s
    return slots, tuple(reversed(strides)), total


def _fuse_codes(codes, valids, live, sizes, strides, total):
    """Traced: dense fused gid per row; NULL keys take the null slot, dead
    rows get ``total`` (matching no group)."""
    fused = jnp.zeros(codes[0].shape, jnp.int32)
    for k in range(len(codes)):
        c = jnp.clip(codes[k].astype(jnp.int32), 0, sizes[k] - 1)
        if valids[k] is not None:
            c = jnp.where(valids[k], c, sizes[k])
        fused = fused + c * strides[k]
    if live is not None:
        fused = jnp.where(live, fused, total)
    return fused


def _decode_codes(r, sizes, slots, strides, has_valid):
    """Traced: representative (code, valid) per group id in ``r``."""
    keys_out = []
    for k in range(len(sizes)):
        ck = (r // strides[k]) % slots[k]
        if has_valid[k]:
            keys_out.append((jnp.minimum(ck, sizes[k] - 1), ck < sizes[k]))
        else:
            keys_out.append((ck, None))
    return keys_out


@jit_memo("kernels._small_agg_fn")
def _small_agg_fn(spec: tuple, num_keys: int, has_valid: tuple,
                  has_live: bool, sizes: tuple):
    """Small-group aggregation with NO sort and NO gather: the group id is
    dictionary-code arithmetic and every aggregate is a vmapped masked
    reduction over the raw rows (measured ~100ms for 8 aggregates over 16M
    rows on v5e vs ~500ms per column for argsort+gather+cumsum — random
    gathers are the TPU's weak point, dense reductions its strength).

    spec: (fn, data_idx, valid_idx, dtype_str, pre) per aggregate over the
    deduped flat operand list; num_keys may be 0 (global aggregate, one
    group).  Float sums need no NaN/Inf rescue here: a NaN only ever lands
    in its own group's reduction (IEEE semantics are exactly SQL's)."""
    slots, strides, total = _code_layout(sizes, has_valid)

    @jax.jit
    def fn(*flat):
        i = 0
        codes, valids = [], []
        for k in range(num_keys):
            codes.append(flat[i])
            i += 1
            if has_valid[k]:
                valids.append(flat[i])
                i += 1
            else:
                valids.append(None)
        live = flat[i] if has_live else None
        i += 1 if has_live else 0
        aggs_flat = flat[i:]
        if num_keys:
            fused = _fuse_codes(codes, valids, live, sizes, strides, total)
        else:
            shape_src = live if live is not None else aggs_flat[0]
            fused = jnp.zeros(shape_src.shape, jnp.int32)
            if live is not None:
                fused = jnp.where(live, fused, total)

        def one_group(g):
            m = fused == g
            outs = []
            outs.append(jnp.sum(m))  # rows-per-group (presence)
            for fname, data_idx, valid_idx, dtype_str, pre in spec:
                dtype = jnp.dtype(dtype_str)
                if fname == "count_star":
                    outs.append(jnp.sum(m).astype(jnp.int64))
                    continue
                x = aggs_flat[data_idx]
                if pre is not None:
                    if pre[0] == "scale":
                        x = x.astype(jnp.float64) / (10.0 ** pre[1])
                    elif pre[0] == "square":
                        x64 = x.astype(jnp.float64)
                        x = x64 * x64
                v = aggs_flat[valid_idx] if valid_idx >= 0 else None
                mv = m if v is None else (m & v)
                if fname == "count":
                    outs.append(jnp.sum(mv).astype(jnp.int64))
                elif fname == "sum":
                    outs.append(jnp.sum(
                        jnp.where(mv, x.astype(dtype), jnp.zeros((), dtype))))
                    outs.append(jnp.sum(mv))  # any-valid flag
                elif fname in ("min", "max", "any_value"):
                    is_min = fname != "max"  # any_value: min is as good as any
                    sent = _sentinel("min" if is_min else "max", x.dtype)
                    masked = jnp.where(mv, x, sent)
                    outs.append(jnp.min(masked) if is_min else jnp.max(masked))
                    outs.append(jnp.sum(mv))
                else:
                    raise NotImplementedError(f"masked aggregate {fname}")
            return tuple(outs)

        cols = jax.vmap(one_group)(jnp.arange(total, dtype=jnp.int32))
        rows_per_group = cols[0]
        presence = rows_per_group > 0
        results = []
        ci = 1
        for fname, data_idx, valid_idx, dtype_str, pre in spec:
            if fname in ("count", "count_star"):
                results.append((cols[ci], None))
                ci += 1
            else:
                # the any-contributor flag applies even without a column
                # validity mask: an empty (or fully dead) group's
                # sum/min/max is NULL, not the fill value
                results.append((cols[ci], cols[ci + 1] > 0))
                ci += 2
        keys_out = _decode_codes(jnp.arange(total, dtype=jnp.int32),
                                 sizes, slots, strides, has_valid)
        return results, presence, keys_out

    return fn


def small_grouped_aggregate(key_cols, live, aggs: Sequence[tuple]):
    """aggs: [(fn, data|None, valid|None, out_dtype, distinct[, pre]), ...]
    (same shape as grouped_reduce's input; distinct unsupported — caller
    falls back).  Returns (results, presence|None, keys_out, num_groups):
    ONE program, zero host syncs, static group count."""
    num_keys = len(key_cols)
    has_valid = tuple(c.valid is not None for c in key_cols)
    sizes = tuple(len(c.dictionary) for c in key_cols)
    flat: list = []
    for c in key_cols:
        flat.append(jnp.asarray(c.data))
        if c.valid is not None:
            flat.append(jnp.asarray(c.valid))
    if live is not None:
        flat.append(jnp.asarray(live))
    base = len(flat)
    flat_ids: dict = {}
    spec = []

    def idx_of(arr) -> int:
        if arr is None:
            return -1
        k = id(arr)
        if k not in flat_ids:
            flat_ids[k] = len(flat) - base
            flat.append(jnp.asarray(arr))
        return flat_ids[k]

    for entry in aggs:
        fn_name, data, valid, dtype, _distinct = entry[:5]
        pre = entry[5] if len(entry) > 5 else None
        if fn_name == "count_star" or data is None:
            # a live-masked count* folds live via the fused gid already
            spec.append(("count", -1, idx_of(valid), "int64", None)
                        if valid is not None else
                        ("count_star", -1, -1, "int64", None))
            continue
        spec.append((fn_name, idx_of(data), idx_of(valid),
                     np.dtype(dtype).str, pre))
    results, presence, keys_out = _small_agg_fn(
        tuple(spec), num_keys, has_valid, live is not None, sizes)(*flat)
    total = 1
    for s, hv in zip(sizes, has_valid):
        total *= s + (1 if hv else 0)
    if num_keys == 0:
        presence = None  # a global aggregate always emits its one row
        total = 1
    return results, presence, keys_out, total


@jit_memo("kernels._group_ids_codes_fn")
def _group_ids_codes_fn(num_keys: int, has_valid: tuple, has_live: bool,
                        sizes: tuple):
    """Fast path for group keys that are ALL small dictionary codes (the
    TPC-H Q1 shape: GROUP BY returnflag, linestatus): the dense group id is
    plain code arithmetic — no multi-key lexsort, and the group count is
    the static product of dictionary sizes (+1 null slot per nullable key),
    so the caller needs NO num_groups host sync.  One program returns
    (perm, gid, presence, decoded representative keys)."""
    slots, strides, total = _code_layout(sizes, has_valid)

    @jax.jit
    def fn(*flat):
        i = 0
        codes, valids = [], []
        for k in range(num_keys):
            codes.append(flat[i])
            i += 1
            if has_valid[k]:
                valids.append(flat[i])
                i += 1
            else:
                valids.append(None)
        live = flat[i] if has_live else None
        fused = _fuse_codes(codes, valids, live, sizes, strides, total)
        perm = jnp.argsort(fused)
        gid = fused[perm]
        r = jnp.arange(total, dtype=gid.dtype)
        presence = (searchsorted(gid, r, side="right")
                    > searchsorted(gid, r))
        keys_out = _decode_codes(r, sizes, slots, strides, has_valid)
        return perm, gid, presence, keys_out

    return fn


def small_codes_group_space(key_cols, limit: int = SMALL_CODES_LIMIT):
    """If every key column is dictionary-encoded with a known-small code
    space, return the static group-space size (else None)."""
    total = 1
    for c in key_cols:
        d = c.dictionary
        if d is None or len(d) == 0:
            return None
        total *= len(d) + (1 if c.valid is not None else 0)
        if total > limit:
            return None
    return total


def group_ids_codes(key_cols, live):
    """Run the small-codes grouping program.  Returns
    (perm, gid, num_groups, presence, keys_out) with num_groups static
    (zero host syncs); ``presence[g]`` marks non-empty groups."""
    num_keys = len(key_cols)
    has_valid = tuple(c.valid is not None for c in key_cols)
    sizes = tuple(len(c.dictionary) for c in key_cols)
    flat: list = []
    for c in key_cols:
        flat.append(jnp.asarray(c.data))
        if c.valid is not None:
            flat.append(jnp.asarray(c.valid))
    if live is not None:
        flat.append(jnp.asarray(live))
    perm, gid, presence, keys_out = _group_ids_codes_fn(
        num_keys, has_valid, live is not None, sizes)(*flat)
    total = 1
    for s, hv in zip(sizes, has_valid):
        total *= s + (1 if hv else 0)
    return perm, gid, total, presence, keys_out


_LIMB_BASE = 1 << 31
_LIMB_COUNT = 5  # 5x31 bits = 155 > 127-bit magnitude; +1 sign limb


def decimal_limb_tables(dictionary) -> list[np.ndarray]:
    """Long-decimal dictionary (python scaled ints) -> 6 int64 limb tables:
    value = sum(limb_k * 2^(31k)) + sign_limb * 2^155.  Each limb is in
    [0, 2^31) (sign limb in {-1, 0}), so per-group int64 sums stay exact
    for up to 2^31 rows — the engine's Int128Math.java: exact wide-decimal
    SUM/AVG runs as ordinary int64 vector sums over limb planes, recombined
    with python bignums per group (spi/type/Int128Math.java's role)."""
    n = len(dictionary)
    tabs = [np.empty(n, np.int64) for _ in range(_LIMB_COUNT + 1)]
    for i, v in enumerate(dictionary):
        x = int(v)
        for k in range(_LIMB_COUNT):
            x, r = divmod(x, _LIMB_BASE)
            tabs[k][i] = r
        tabs[_LIMB_COUNT][i] = x  # 0 or -1
    return tabs


def combine_limb_sums(sums) -> int:
    """Per-group limb sums (python ints) -> exact scaled-int total."""
    total = 0
    for k in range(_LIMB_COUNT):
        total += int(sums[k]) << (31 * k)
    total += int(sums[_LIMB_COUNT]) << (31 * _LIMB_COUNT)
    return total


_SENTINELS = {
    "min": {
        "i": lambda dt: jnp.iinfo(dt).max,
        "f": lambda dt: jnp.inf,
        "b": lambda dt: True,
    },
    "max": {
        "i": lambda dt: jnp.iinfo(dt).min,
        "f": lambda dt: -jnp.inf,
        "b": lambda dt: False,
    },
}


def _sentinel(fn: str, dtype) -> object:
    kind = np.dtype(dtype).kind
    k = "f" if kind == "f" else ("b" if kind == "b" else "i")
    return _SENTINELS[fn][k](dtype)


@jit_memo("kernels._reduce_fn")
def _reduce_fn(spec: tuple, cap: int):
    """spec: tuple of (fn, data_idx, valid_idx, dtype_str, distinct, pre)
    per aggregate; data_idx/valid_idx index the DEDUPED flat input arrays
    (-1 = absent), so aggregates sharing a column or a validity/live mask
    share one prefix scan.  ``pre`` applies elementwise prep INSIDE the
    compiled program (("scale", s) = scale-free f64 avg state; ("square",) =
    x^2 f64 variance state) — the hot path never runs eager full-size ops.

    All reductions are prefix-scan + boundary-gather over the sorted rows
    (gid is nondecreasing): XLA scatters serialize on TPU; the scan path is
    log-depth vector work."""

    @jax.jit
    def fn(perm, gid, *flat):
        outs = []
        n = perm.shape[0]
        ones = jnp.ones(perm.shape, dtype=jnp.int64)
        starts = searchsorted(gid, jnp.arange(cap))
        # end of group g = first row with gid > g (side='right'): when
        # num_groups == cap, ends[cap-1] must STOP at the dead-row region
        # (dead rows carry gid >= cap and form their own trailing segments)
        ends = searchsorted(gid, jnp.arange(cap), side="right")
        nonempty = ends > starts
        seg_first = jnp.concatenate(
            [jnp.ones((1,), jnp.bool_), gid[1:] != gid[:-1]])

        sorted_cache: dict = {}

        def sorted_of(idx):
            if idx not in sorted_cache:
                sorted_cache[idx] = flat[idx][perm]
            return sorted_cache[idx]

        # trace-time memo keyed by LOGICAL identity: aggregates sharing a
        # (column, validity, dtype, prep) emit one scan, not one each — the
        # TPU compiler segfaults on dozens of megarow cumsums in one fusion
        _memo: dict = {}

        def seg_sum_raw(x, acc_dtype, key=None):
            mkey = None if key is None else ("raw",) + key
            if mkey is not None and mkey in _memo:
                return _memo[mkey]
            cs = jnp.cumsum(x.astype(acc_dtype))
            hi = cs[jnp.maximum(ends - 1, 0)]
            lo = jnp.where(starts > 0, cs[jnp.maximum(starts - 1, 0)],
                           jnp.zeros((), acc_dtype))
            out = jnp.where(nonempty, hi - lo, jnp.zeros((), acc_dtype))
            if mkey is not None:
                _memo[mkey] = out
            return out

        def seg_sum(x, acc_dtype, ieee: bool, key=None):
            if np.dtype(acc_dtype).kind != "f" or not ieee:
                return seg_sum_raw(x, acc_dtype, key)
            mkey = None if key is None else ("ieee",) + key
            if mkey is not None and mkey in _memo:
                return _memo[mkey]
            # float path: a NaN/Inf anywhere would poison the global prefix
            # sum for every LATER segment; zero them out and restore the
            # IEEE result per segment
            xa = x.astype(acc_dtype)
            finite = jnp.isfinite(xa)
            base = seg_sum_raw(jnp.where(finite, xa, 0.0), acc_dtype)
            has_nan = seg_sum_raw(jnp.isnan(xa).astype(jnp.int32),
                                  jnp.int32) > 0
            has_pos = seg_sum_raw((xa == jnp.inf).astype(jnp.int32),
                                  jnp.int32) > 0
            has_neg = seg_sum_raw((xa == -jnp.inf).astype(jnp.int32),
                                  jnp.int32) > 0
            out = jnp.where(has_pos, jnp.inf, base)
            out = jnp.where(has_neg, -jnp.inf, out)
            out = jnp.where(has_nan | (has_pos & has_neg), jnp.nan, out)
            out = out.astype(acc_dtype)
            if mkey is not None:
                _memo[mkey] = out
            return out

        def seg_minmax(x, is_min: bool):
            op = jnp.minimum if is_min else jnp.maximum

            def comb(a, b):
                fa, va = a
                fb, vb = b
                return (fa | fb, jnp.where(fb, vb, op(va, vb)))

            _, running = jax.lax.associative_scan(comb, (seg_first, x))
            return running[jnp.maximum(ends - 1, 0)]

        def seg_any(valid_idx):
            v = sorted_of(valid_idx)
            return seg_sum_raw(v.astype(jnp.int32), jnp.int32,
                               ("any", valid_idx)) > 0

        for fname, data_idx, valid_idx, dtype_str, distinct, pre in spec:
            dtype = jnp.dtype(dtype_str)
            if fname == "count_star":
                if valid_idx >= 0:  # the live mask of a padded batch
                    c = sorted_of(valid_idx).astype(jnp.int64)
                    outs.append((seg_sum_raw(c, jnp.int64,
                                             ("count", valid_idx)), None))
                else:
                    outs.append((seg_sum_raw(ones, jnp.int64,
                                             ("count", -1)), None))
                continue
            data = sorted_of(data_idx)
            # integer-sourced values can never be NaN/Inf: their float sums
            # skip the IEEE rescue scans entirely
            src_float = np.dtype(data.dtype).kind == "f"
            if pre is not None:
                if pre[0] == "scale":
                    data = data.astype(jnp.float64) / (10.0 ** pre[1])
                elif pre[0] == "square":
                    x64 = data.astype(jnp.float64)
                    data = x64 * x64
            valid = sorted_of(valid_idx) if valid_idx >= 0 else None
            skey = (data_idx, valid_idx, np.dtype(dtype_str).str, pre)
            if distinct:
                # rows sorted by group key only; distinct needs per-(group,
                # value) dedup: mark first occurrence within (gid, valid,
                # value) runs — validity participates so a NULL row whose
                # storage fill collides with a real value stays its own run
                if np.dtype(data.dtype).kind == "f":
                    data = _canon_float(data)  # NaN is ONE distinct value
                if valid is not None:
                    order = jnp.lexsort((data, valid, gid))
                    v2 = valid[order]
                else:
                    order = jnp.lexsort((data, gid))
                    v2 = None
                d2, g2 = data[order], gid[order]
                first = jnp.concatenate(
                    [jnp.ones((1,), jnp.bool_), _neq(d2[1:], d2[:-1]) | (g2[1:] != g2[:-1])]
                )
                if v2 is not None:
                    first = first | jnp.concatenate(
                        [jnp.ones((1,), jnp.bool_), v2[1:] != v2[:-1]])
                keep = first if v2 is None else (first & v2)
                # d2/g2 reorder rows within each segment only: the segment
                # boundary positions (starts/ends) are unchanged
                if fname in ("count", "count_star"):
                    outs.append((seg_sum_raw(keep.astype(jnp.int64),
                                             jnp.int64), None))
                    continue
                if fname == "sum":
                    x = jnp.where(keep, d2, jnp.zeros((), dtype))
                    anyk = seg_sum_raw(keep.astype(jnp.int32), jnp.int32) > 0
                    outs.append((seg_sum(x, dtype, src_float), anyk))
                    continue
                raise NotImplementedError(f"distinct {fname}")
            if fname == "count":
                if valid is None:
                    outs.append((seg_sum_raw(ones, jnp.int64,
                                             ("count", -1)), None))
                else:
                    outs.append((seg_sum_raw(valid.astype(jnp.int64),
                                             jnp.int64,
                                             ("count", valid_idx)), None))
            elif fname == "sum":
                x = data if valid is None else jnp.where(valid, data, jnp.zeros((), data.dtype))
                s = seg_sum(x, dtype, src_float, ("sum",) + skey)
                anyv = None if valid is None else seg_any(valid_idx)
                outs.append((s, anyv))
            elif fname in ("min", "max"):
                sent = _sentinel(fname, data.dtype)
                x = data if valid is None else jnp.where(valid, data, sent)
                r = seg_minmax(x, fname == "min")
                anyv = None if valid is None else seg_any(valid_idx)
                outs.append((r, anyv))
            elif fname == "any_value":
                # gather at each segment's first VALID row: re-sort rows so
                # invalid ones go last within their segment, then take starts
                if valid is None:
                    rows = jnp.minimum(starts, n - 1)
                    outs.append((data[rows], None))
                else:
                    order = jnp.lexsort((~valid, gid))
                    rows = jnp.minimum(starts, n - 1)
                    outs.append((data[order][rows], seg_any(valid_idx)))
            else:
                raise NotImplementedError(f"aggregate {fname}")
        return outs

    return fn


@jit_memo("kernels._finalize_fn")
def _finalize_fn(plan: tuple):
    """One compiled program for aggregation finalization (avg division,
    variance combine, output casts) over the tiny per-group arrays — the
    output columns stay ON DEVICE (the collective exchange path feeds them
    straight into all_to_all) and the host pays zero per-op dispatches.

    plan: per output column, one of
      ("copy", dtype_str|None, has_valid)            passthrough + cast
      ("avg_final", dtype_str, has_valid)            sum/count -> mean
      ("stat_final", fn, dtype_str, has_valid)       (s, sq, n) -> var/stddev
      ("count", None, has_valid)                     cast int64, drop valid
    inputs: flat (data [, valid]) per plan entry's source arity."""

    @jax.jit
    def fn(*flat):
        outs = []
        i = 0
        for entry in plan:
            kind = entry[0]
            if kind == "copy":
                _, dtype_str, has_valid = entry
                d = flat[i]
                i += 1
                v = None
                if has_valid:
                    v = flat[i]
                    i += 1
                if dtype_str is not None:
                    d = d.astype(jnp.dtype(dtype_str))
                outs.append((d, v))
            elif kind == "count":
                _, _, has_valid = entry
                d = flat[i]
                i += 1
                if has_valid:
                    i += 1  # counts are never NULL
                outs.append((d.astype(jnp.int64), None))
            elif kind == "avg_final":
                _, dtype_str, has_valid = entry
                s = flat[i]
                i += 1
                sv = None
                if has_valid:
                    sv = flat[i]
                    i += 1
                c = flat[i]
                i += 1
                cnt = jnp.maximum(c, 1)
                vals = s / cnt
                valid = c > 0
                if sv is not None:
                    valid = valid & sv
                outs.append((vals.astype(jnp.dtype(dtype_str)), valid))
            elif kind == "stat_final":
                _, fname, dtype_str, has_valid = entry
                s = flat[i]
                i += 1
                sv = None
                if has_valid:
                    sv = flat[i]
                    i += 1
                q = flat[i]
                i += 1
                c = flat[i]
                i += 1
                n = c.astype(jnp.float64)
                safe_n = jnp.maximum(n, 1.0)
                mean = s / safe_n
                m2 = jnp.maximum(q - safe_n * mean * mean, 0.0)
                if fname in ("var_pop", "stddev_pop"):
                    var = m2 / safe_n
                    valid = n > 0
                else:  # sample variance: NULL for fewer than 2 values
                    var = m2 / jnp.maximum(n - 1.0, 1.0)
                    valid = n > 1
                vals = jnp.sqrt(var) if fname.startswith("stddev") else var
                if sv is not None:
                    valid = valid & sv
                outs.append((vals.astype(jnp.dtype(dtype_str)), valid))
            else:
                raise NotImplementedError(kind)
        return outs

    return fn


def finalize_groups(plan: Sequence[tuple], arrays: Sequence):
    """Run the cached finalize program; ``arrays`` is the flat (device or
    host) input list matching ``plan``."""
    return _finalize_fn(tuple(plan))(*[jnp.asarray(a) for a in arrays])


_FAILED_REDUCE_SPECS: set = set()

_PALLAS_STATE = {"enabled": None}


def _pallas_enabled() -> bool:
    import os

    if _PALLAS_STATE["enabled"] is None:
        mode = os.environ.get("TRINO_TPU_PALLAS", "1")
        if mode == "0":
            _PALLAS_STATE["enabled"] = False
        else:
            from ..ops.pallas_kernels import pallas_available

            # compiled kernels only beat XLA on real TPU lanes; interpret
            # mode is for tests (force with TRINO_TPU_PALLAS=force)
            _PALLAS_STATE["enabled"] = pallas_available() and (
                mode == "force" or jax.default_backend() == "tpu")
    return _PALLAS_STATE["enabled"]


def _pallas_f32_sum(perm, gid, cap: int, data, valid):
    """REAL-sum fast path: blockwise VMEM accumulation instead of XLA's
    scatter segment_sum (ops/pallas_kernels.py).  Returns (sums, anyvalid)
    or None when pallas fails (flag flips off, XLA takes over)."""
    from ..ops import pallas_kernels as PK

    try:
        interpret = jax.default_backend() != "tpu"
        vals = jnp.asarray(data)[perm]
        lv = None if valid is None else jnp.asarray(valid)[perm]
        s = PK.masked_segment_sum_f32(vals, gid, lv, cap, interpret=interpret)
        anyv = None
        if valid is not None:  # the validity bit is one cheap segment_max
            anyv = jax.ops.segment_max(lv, gid, cap)
        return s, anyv
    except Exception:  # noqa: BLE001 — pallas unavailable: permanent fallback
        _PALLAS_STATE["enabled"] = False
        return None


def grouped_reduce(
    perm,
    gid,
    num_groups: int,
    aggs: Sequence[tuple],
) -> list[tuple[np.ndarray, Optional[np.ndarray]]]:
    """aggs: [(fn, data|None, valid|None, out_dtype, distinct[, pre]), ...].

    Returns per-agg (values, valid|None) arrays of length num_groups.
    Input arrays are DEDUPED by object identity before entering the jitted
    program, so aggregates over the same column / live mask share scans."""
    cap = bucket(num_groups)
    results: list = [None] * len(aggs)
    spec = []
    flat: list = []
    flat_ids: dict = {}
    xla_slots = []

    def idx_of(arr) -> int:
        if arr is None:
            return -1
        k = id(arr)
        if k not in flat_ids:
            flat_ids[k] = len(flat)
            flat.append(jnp.asarray(arr))
        return flat_ids[k]

    for idx, entry in enumerate(aggs):
        fn, data, valid, dtype, distinct = entry[:5]
        pre = entry[5] if len(entry) > 5 else None
        if (fn == "sum" and data is not None and not distinct and pre is None
                and np.dtype(dtype) == np.float32 and cap <= 64
                and _pallas_enabled()):
            out = _pallas_f32_sum(jnp.asarray(perm), jnp.asarray(gid), cap,
                                  data, valid)
            if out is not None:
                results[idx] = (out[0][:num_groups],
                                None if out[1] is None
                                else out[1][:num_groups])
                continue
        if fn == "count_star" or data is None:
            spec.append(("count_star", -1, idx_of(valid), "int64", False,
                         None))
            xla_slots.append(idx)
            continue
        spec.append((fn, idx_of(data), idx_of(valid), np.dtype(dtype).str,
                     bool(distinct), pre))
        xla_slots.append(idx)

    # the TPU compiler segfaults on programs mixing >=2 int64 prefix sums
    # (x64 lanes are emulated) with a float64 prefix sum: split the specs
    # into an integer-accumulator program and a float program
    def _int_class(s) -> bool:
        fn = s[0]
        if fn in ("count", "count_star"):
            return True
        return fn == "sum" and np.dtype(s[3]).kind in "iu"

    def _run(members) -> None:
        """Run one compiled program for ``members``; on a TPU compiler
        crash (flaky SIGSEGV on large mixed-dtype scan fusions) split the
        program in half and retry — smaller programs always compile.
        Failed (spec, cap) combos are remembered: the broken compile is
        NOT cached by jax, so without the memo every warm run would re-pay
        the multi-second failing compile before splitting."""
        # remap flat indices to the subset actually used by this program
        sub_flat: list = []
        remap: dict = {}

        def sub_idx(fi: int) -> int:
            if fi < 0:
                return -1
            if fi not in remap:
                remap[fi] = len(sub_flat)
                sub_flat.append(flat[fi])
            return remap[fi]

        sub_spec = tuple(
            (s[0], sub_idx(s[1]), sub_idx(s[2]), s[3], s[4], s[5])
            for _, s in members)

        def split() -> None:
            mid = len(members) // 2
            _run(members[:mid])
            _run(members[mid:])

        if (sub_spec, cap) in _FAILED_REDUCE_SPECS:
            split()
            return
        try:
            outs = _reduce_fn(sub_spec, cap)(
                jnp.asarray(perm), jnp.asarray(gid), *sub_flat)
        except jax.errors.JaxRuntimeError:
            # remote-compile crash (the TPU compiler helper segfaults on
            # some large mixed-dtype scan fusions); genuine trace errors
            # (NotImplementedError, dtype bugs) re-raise immediately
            if len(members) == 1:
                raise
            _FAILED_REDUCE_SPECS.add((sub_spec, cap))
            split()
            return
        for (spec_i, _), (data, valid) in zip(members, outs):
            idx = xla_slots[spec_i]
            results[idx] = (data[:num_groups],
                            None if valid is None else valid[:num_groups])

    # the TPU compiler is unreliable on programs mixing several int64
    # prefix sums (x64 lanes are emulated) with float64 prefix sums: run
    # an integer-accumulator program and a float program, each with the
    # split-retry ladder above
    int_members = [(i, s) for i, s in enumerate(spec) if _int_class(s)]
    flt_members = [(i, s) for i, s in enumerate(spec) if not _int_class(s)]
    if int_members:
        _run(int_members)
    if flt_members:
        _run(flt_members)
    return results


@jit_memo("kernels._keys_out_fn")
def _keys_out_fn(has_valid: tuple, cap: int):
    @jax.jit
    def fn(perm, gid, *flat):
        # gid is sorted: group g's representative is its FIRST sorted row —
        # a binary-search gather, not a scatter (scatters serialize on TPU)
        n = perm.shape[0]
        starts = jnp.minimum(searchsorted(gid, jnp.arange(cap)), n - 1)
        rows = perm[starts]
        out = []
        i = 0
        for hv in has_valid:
            d = flat[i][rows]
            i += 1
            if hv:
                v = flat[i][rows]
                i += 1
                out.append((d, v))
            else:
                out.append((d, None))
        return out

    return fn


def group_keys_out(perm, gid, num_groups: int, keys: Sequence[tuple]):
    """Materialize one representative key row per group (device arrays out;
    dead rows carry gids >= cap-scatter range and are dropped).  One
    compiled program per (key structure, cap) — no eager scatters."""
    cap = bucket(num_groups)
    has_valid = tuple(v is not None for _, v in keys)
    flat = []
    for data, valid in keys:
        flat.append(jnp.asarray(data))
        if valid is not None:
            flat.append(jnp.asarray(valid))
    outs = _keys_out_fn(has_valid, cap)(
        jnp.asarray(perm), jnp.asarray(gid), *flat)
    return [(d[:num_groups], None if v is None else v[:num_groups])
            for d, v in outs]


# ---------------------------------------------------------------------------
# sort


_HOST_SORT_MAX = 1 << 16  # below this, device dispatch latency dominates


def _sort_columns(keys: Sequence[tuple], xp):
    """Build lexsort columns (shared by host/device paths); ``xp`` is numpy
    or jax.numpy."""
    sort_cols = []
    for data, valid, ascending, nulls_first in reversed(list(keys)):
        d = xp.asarray(data)
        kind = np.dtype(d.dtype).kind
        if not ascending:
            if kind == "b":
                d = ~d
            elif kind == "f":
                d = -d.astype(xp.float64)
            else:
                # bitwise NOT is a bijective order reversal; unary minus maps
                # INT64_MIN to itself under two's-complement wraparound
                d = ~d.astype(xp.int64)
        if valid is not None:
            # canonicalize NULL rows' payload FIRST (before NaN ranking):
            # two NULLs must tie exactly on every derived column, or their
            # garbage data would decide the less-significant keys
            v = xp.asarray(valid)
            d = xp.where(v, d, xp.zeros((), d.dtype))
        nan_rank = None
        if kind == "f":
            # NaN sorts largest (Trino convention) via its own rank column —
            # mapping NaN into the value domain (+/-inf) would tie with real
            # infinities; the rank is more significant than the value
            nan = xp.isnan(d)
            nan_rank = xp.where(nan, 1 if ascending else 0,
                                0 if ascending else 1)
            d = xp.where(nan, xp.zeros((), d.dtype), d)
        sort_cols.append(d)
        if nan_rank is not None:
            sort_cols.append(nan_rank)
        if valid is not None:
            # secondary column is sorted after; null rank must be primary
            null_rank = xp.where(v, 1, 0) if nulls_first else xp.where(v, 0, 1)
            sort_cols.append(null_rank)
    return sort_cols


@jit_memo("kernels._device_sort_fn")
def _device_sort_fn(num_keys: int, key_meta: tuple, col_has_valid: tuple,
                    has_live: bool, out_n: Optional[int]):
    """One jitted program: lexsort + gather every payload column (+ live).
    ``key_meta``: (has_valid, ascending, nulls_first) per key, major->minor.
    Dead rows sort last regardless of key values (the live rank is the most
    significant sort column), so a ``live``-masked batch stays valid after
    sorting and ``out_n`` (top-N) keeps the best live rows."""

    @jax.jit
    def fn(*flat):
        i = 0
        keys = []
        for hv, asc, nf in key_meta:
            d = flat[i]
            i += 1
            v = None
            if hv:
                v = flat[i]
                i += 1
            keys.append((d, v, asc, nf))
        cols = []
        for hv in col_has_valid:
            d = flat[i]
            i += 1
            v = None
            if hv:
                v = flat[i]
                i += 1
            cols.append((d, v))
        live = flat[i] if has_live else None
        sort_cols = _sort_columns(keys, jnp)
        if live is not None:
            sort_cols.append(~live)  # most significant: dead rows last
        perm = jnp.lexsort(tuple(sort_cols))
        if out_n is not None:
            perm = perm[:out_n]
        outs = [(d[perm], None if v is None else v[perm]) for d, v in cols]
        return outs, (None if live is None else live[perm])

    return fn


def device_sort(keys: Sequence[tuple], cols: Sequence[tuple], live,
                out_n: Optional[int] = None):
    """keys: [(data, valid|None, ascending, nulls_first), ...] major->minor;
    cols: [(data, valid|None), ...] payload.  Returns (sorted cols, sorted
    live) — all device, zero host syncs."""
    key_meta = tuple((v is not None, bool(a), bool(nf))
                     for _, v, a, nf in keys)
    col_has_valid = tuple(v is not None for _, v in cols)
    flat: list = []
    for d, v, _, _ in keys:
        flat.append(jnp.asarray(d))
        if v is not None:
            flat.append(jnp.asarray(v))
    for d, v in cols:
        flat.append(jnp.asarray(d))
        if v is not None:
            flat.append(jnp.asarray(v))
    if live is not None:
        flat.append(jnp.asarray(live))
    return _device_sort_fn(len(keys), key_meta, col_has_valid,
                           live is not None, out_n)(*flat)


def sort_perm(keys: Sequence[tuple]) -> np.ndarray:
    """keys: [(data, valid|None, ascending, nulls_first), ...] in major-to-
    minor significance order.  Returns the stable sorting permutation.

    Large/device-resident inputs run as one ``jnp.lexsort`` (XLA variadic
    sort on the chip).  Small host-resident inputs (the common post-
    aggregation final sort: a handful of rows) run ``np.lexsort`` on host —
    shipping 10 tiny columns through a tunneled device costs ~1000x the
    sort itself."""
    host = keys and all(
        isinstance(k[0], np.ndarray)
        and (k[1] is None or isinstance(k[1], np.ndarray))
        for k in keys) and keys[0][0].shape[0] <= _HOST_SORT_MAX
    if host:
        return np.lexsort(tuple(_sort_columns(keys, np)))
    perm = jnp.lexsort(tuple(_sort_columns(keys, jnp)))
    return np.asarray(perm)


# ---------------------------------------------------------------------------
# join: sorted-build + binary-search probe

_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)


def _mix64(h):
    h = (h ^ (h >> 30)) * jnp.uint64(_M1)
    h = (h ^ (h >> 27)) * jnp.uint64(_M2)
    return h ^ (h >> 31)


def _f64_hash_word(a):
    """Full-entropy uint64 hash word for a canonical float64 column, built
    arithmetically — the TPU x64 rewrite cannot compile any 64-bit bitcast
    (f64->u64, f64->2xu32 and frexp all fail).  The value is range-reduced
    into an f32-friendly window by a log2-derived class, then split into
    three float32 words whose cascade captures the whole 53-bit significand
    (24*3 > 53), so equal doubles hash equal and distinct doubles collide
    with negligible probability across the full f64 range."""
    fin = jnp.isfinite(a)
    mag = jnp.abs(a)
    safe_mag = jnp.where(mag > 0, mag, 1.0)
    cls = jnp.clip(jnp.floor(jnp.log2(safe_mag) / 120.0), -9.0, 9.0)
    s = 2.0 ** (-60.0 * cls)  # applied twice; 2**(-120*cls) would overflow
    scaled = jnp.where(fin, a * s * s, 0.0)
    w1 = scaled.astype(jnp.float32)
    r1 = scaled - w1.astype(jnp.float64)
    w2 = r1.astype(jnp.float32)
    r2 = r1 - w2.astype(jnp.float64)
    w3 = r2.astype(jnp.float32)
    tag = jnp.where(jnp.isnan(a), 3, jnp.where(a == jnp.inf, 1,
                    jnp.where(a == -jnp.inf, 2, 0)))
    meta = (cls.astype(jnp.int32) + 16) | (tag.astype(jnp.int32) << 8)

    def u32(w):
        return jax.lax.bitcast_convert_type(w, jnp.uint32).astype(jnp.uint64)

    lo = u32(w1) | (u32(w2) << 32)
    hi = u32(w3) | (meta.astype(jnp.uint32).astype(jnp.uint64) << 32)
    return _mix64(lo) ^ hi


def hash_combine(datas: Sequence) -> jnp.ndarray:
    """Combine n key columns into one uint64 hash lane (splitmix64 mix).

    Used for candidate equality (verified exactly afterwards) and for
    partition assignment (no verification needed)."""
    h = jnp.zeros(jnp.asarray(datas[0]).shape, dtype=jnp.uint64)
    for d in datas:
        x = jnp.asarray(d)
        if x.dtype == jnp.bool_:
            x = x.astype(jnp.uint64)
        elif np.dtype(x.dtype).kind == "f":
            x = _f64_hash_word(_canon_float(x.astype(jnp.float64)))
        else:
            x = x.astype(jnp.int64).astype(jnp.uint64)
        h = _mix64(h ^ (x + jnp.uint64(0x9E3779B97F4A7C15)))
    return h


@jax.jit
def _sorted_hash(h):
    perm = jnp.argsort(h)
    return h[perm], perm


class JoinTable:
    """Sorted-hash build side (the PagesHash/LookupSource equivalent)."""

    __slots__ = ("sorted_hash", "perm", "key_datas", "_has_null", "num_rows")

    def __init__(self, sorted_hash, perm, key_datas, has_null_key, num_rows):
        self.sorted_hash = sorted_hash
        self.perm = perm  # build row index per sorted-hash position
        self.key_datas = key_datas  # original (unsorted) key arrays for verify
        # host bool, or a device scalar fetched lazily on first access (its
        # async copy usually lands before any probe asks)
        self._has_null = has_null_key
        self.num_rows = num_rows

    @property
    def has_null_key(self) -> bool:
        if not isinstance(self._has_null, bool):
            from . import syncguard as SG

            self._has_null = bool(
                SG.fetch(self._has_null, "kernels.has-null-key"))
        return self._has_null


def build_join_table(keys: Sequence[tuple], num_rows: Optional[int] = None) -> JoinTable:
    """keys: [(data, valid|None), ...] over build rows.  Rows with any NULL
    key never match (SQL equi-join) — they are excluded via a reserved hash.

    Empty ``keys`` (with explicit ``num_rows``) builds a cross-join table:
    every probe row matches every build row (nested-loop fallback, mirrors
    operator/join/NestedLoopJoinOperator.java:45)."""
    if not keys:
        return JoinTable(None, None, [], False, int(num_rows or 0))
    datas = [jnp.asarray(d) for d, _ in keys]
    n = int(datas[0].shape[0]) if datas else 0
    h = hash_combine(datas)
    null_mask = None
    for _, v in keys:
        if v is not None:
            nm = ~jnp.asarray(v)
            null_mask = nm if null_mask is None else (null_mask | nm)
    has_null = False
    if null_mask is not None:
        # stays a device scalar: building the table costs zero blocking
        # syncs; JoinTable.has_null_key fetches lazily (async copy already
        # in flight, usually landed by first access)
        has_null = jnp.any(null_mask)
        try:
            has_null.copy_to_host_async()
        except AttributeError:
            pass
        # reserved sentinel: max uint64 never produced for probes (probes with
        # null keys are masked out before lookup)
        h = jnp.where(null_mask, jnp.uint64(0xFFFFFFFFFFFFFFFF), h)
    sh, perm = _sorted_hash(h)
    return JoinTable(sh, perm, datas, has_null, n)


@jit_memo("kernels._probe_ranges_fn")
def _probe_ranges_fn():
    @jax.jit
    def fn(sorted_hash, probe_hash):
        lo = searchsorted(sorted_hash, probe_hash, side="left")
        hi = searchsorted(sorted_hash, probe_hash, side="right")
        return lo, hi - lo

    return fn


_PAIR_PAD = 4  # speculative expand headroom over bucket(n_probe)


@jit_memo("kernels._expand_fn")
def _expand_fn(cap: int):
    """Expansion kernel sized to a power-of-two bucket ``cap`` >= total so
    varying per-batch match counts reuse a handful of compiled programs;
    slots >= total produce clamped garbage the caller slices off."""

    @jax.jit
    def fn(lo, counts, perm):
        n = counts.shape[0]
        ends = jnp.cumsum(counts)
        starts = ends - counts
        slot = jnp.arange(cap)
        probe_id = jnp.clip(searchsorted(ends, slot, side="right"), 0, n - 1)
        within = slot - starts[probe_id]
        build_pos = lo[probe_id] + within
        return probe_id, perm[jnp.clip(build_pos, 0, perm.shape[0] - 1)]

    return fn


def probe_join_table(
    table: JoinTable, probe_keys: Sequence[tuple], live=None
) -> tuple[np.ndarray, np.ndarray]:
    """Returns (probe_idx, build_idx) pairs of ALL equi-matches, exactly
    verified.  Caller layers inner/left/semi semantics on top.  ``live``
    masks padded/filtered-out probe rows (they never match).

    ``n_probe`` must be passed for the keyless (cross-join) table."""
    if not table.key_datas:  # cross join
        nb = table.num_rows
        n_probe = probe_keys  # caller passes the row count in place of keys
        assert isinstance(n_probe, int), "cross-join probe needs a row count"
        return (np.repeat(np.arange(n_probe, dtype=np.int64), nb),
                np.tile(np.arange(nb, dtype=np.int64), n_probe))
    pdatas = [jnp.asarray(d) for d, _ in probe_keys]
    n_probe = int(pdatas[0].shape[0])
    if n_probe == 0 or table.num_rows == 0:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    ph = hash_combine(pdatas)
    pnull = None
    for _, v in probe_keys:
        if v is not None:
            nm = ~jnp.asarray(v)
            pnull = nm if pnull is None else (pnull | nm)
    if pnull is not None:
        # flip to a hash that cannot exist in the table's non-null region
        ph = jnp.where(pnull, jnp.uint64(0xFFFFFFFFFFFFFFFE), ph)
    lo, counts = _probe_ranges_fn()(table.sorted_hash, ph)
    if pnull is not None:
        counts = jnp.where(pnull, 0, counts)
    if live is not None:
        counts = jnp.where(jnp.asarray(live), counts, 0)
    if table.has_null_key:
        # sentinel region must never match
        counts = jnp.where(ph == jnp.uint64(0xFFFFFFFFFFFFFFFF), 0, counts)
    from . import syncguard as SG

    total_dev = jnp.sum(counts)
    if os.environ.get("TRINO_TPU_LEGACY_EXPAND") == "1":
        # legacy two-fetch expand: block on the exact candidate total, size
        # the bucket from it, then fetch the verified pairs (kept for
        # equivalence testing against the padded single-fetch path)
        total = int(SG.fetch(total_dev, "kernels.pair-total"))
        if total == 0:
            return np.empty(0, np.int64), np.empty(0, np.int64)
        probe_id, build_id = _expand_fn(bucket(total))(lo, counts, table.perm)
        probe_id, build_id = probe_id[:total], build_id[:total]
        ok = jnp.ones((total,), jnp.bool_)
        for (pd, pv), bd in zip(probe_keys, table.key_datas):
            p, b = jnp.asarray(pd)[probe_id], bd[build_id]
            ok = ok & ~_neq(p, b)
        keep, probe_id, build_id = SG.fetch(
            (ok, probe_id, build_id), "kernels.pair-batch")
        return probe_id[keep], build_id[keep]

    def expand_verify(cap: int):
        """Padded expand + exact verify (hash candidates -> equality on
        every key column; float equality mirrors the grouping semantics:
        NaN matches NaN).  Slots beyond the total are masked out."""
        probe_id, build_id = _expand_fn(cap)(lo, counts, table.perm)
        ok = jnp.arange(cap) < total_dev
        for (pd, pv), bd in zip(probe_keys, table.key_datas):
            p, b = jnp.asarray(pd)[probe_id], bd[build_id]
            ok = ok & ~_neq(p, b)
        return ok, probe_id, build_id

    # padded single-fetch expand: speculate a bucket from the probe width,
    # land the total WITH the verified pairs in one device->host round trip
    # (the blocking total-sync this replaces was half the legacy path's RTTs)
    cap = bucket(max(n_probe, 1)) * _PAIR_PAD
    total, keep, probe_id, build_id = SG.fetch(
        (total_dev,) + expand_verify(cap), "kernels.pair-batch")
    if int(total) > cap:  # rare: speculation too small — exact-size re-run
        SG.count_overflow()
        total, keep, probe_id, build_id = SG.fetch(
            (total_dev,) + expand_verify(bucket(int(total))),
            "kernels.pair-batch")
    return probe_id[keep], build_id[keep]


# ---------------------------------------------------------------------------
# partitioning (shuffle producer — PagePartitioner.partitionPage equivalent)


@jit_memo("kernels._domain_fn")
def _domain_fn(has_valid: bool, has_live: bool, dict_len: int):
    """Build-key domain for dynamic filtering, all on device: returns
    (valid_count, non-NaN count, min, max, presence-per-dictionary-code).
    Presence uses sort + binary search, not scatter (scatters serialize)."""

    @jax.jit
    def fn(data, *rest):
        i = 0
        valid = rest[i] if has_valid else None
        i += 1 if has_valid else 0
        live = rest[i] if has_live else None
        eligible = None
        if valid is not None:
            eligible = valid
        if live is not None:
            eligible = live if eligible is None else (eligible & live)
        kind = np.dtype(data.dtype).kind
        if eligible is None:
            cnt = jnp.asarray(data.shape[0], jnp.int64)
        else:
            cnt = jnp.sum(eligible)
        if kind == "f":
            nan = jnp.isnan(data)
            ok = ~nan if eligible is None else (eligible & ~nan)
            cnt_nonnan = jnp.sum(ok)
        else:
            ok = eligible
            cnt_nonnan = cnt
        big = _sentinel("min", data.dtype)
        small = _sentinel("max", data.dtype)
        vmin = jnp.min(data if ok is None else jnp.where(ok, data, big))
        vmax = jnp.max(data if ok is None else jnp.where(ok, data, small))
        if dict_len:
            sent = jnp.asarray(dict_len, data.dtype)
            codes = jnp.sort(data if eligible is None
                             else jnp.where(eligible, data, sent))
            r = jnp.arange(dict_len, dtype=data.dtype)
            presence = (jnp.searchsorted(codes, r, side="right")
                        > jnp.searchsorted(codes, r, side="left"))
        else:
            presence = jnp.zeros((0,), jnp.bool_)
        return cnt, cnt_nonnan, vmin, vmax, presence

    return fn


def _device_domain(data, valid, live, dict_len: int):
    flat = [jnp.asarray(data)]
    if valid is not None:
        flat.append(jnp.asarray(valid))
    if live is not None:
        flat.append(jnp.asarray(live))
    return _domain_fn(valid is not None, live is not None, dict_len)(*flat)


@jit_memo("kernels._compact_fn")
def _compact_fn(n_cols: int, valid_flags: tuple, has_live_out: bool, cap: int):
    """Gather live rows to the front and slice to ``cap`` lanes (one stable
    bool sort + gathers, all on device)."""

    @jax.jit
    def fn(live, *flat):
        order = jnp.argsort(~live, stable=True)[:cap]
        out = [x[order] for x in flat]
        if has_live_out:
            out.append(live[order])
        return tuple(out)

    return fn


def compact_device_batch(batch, live_count: int):
    """Compact a live-masked device batch down to bucket(live_count) lanes.
    Dead lanes beyond the bucket are dropped; the (padded) tail keeps a live
    mask.  Used by blocking operators whose cost is O(lanes log lanes): a
    join output riding a fat probe shape with few survivors would otherwise
    drag its dead lanes through every downstream sort."""
    from ..spi.batch import Column, ColumnBatch

    cap = bucket(max(live_count, 1))
    if cap >= batch.num_rows:
        return batch
    flat = []
    valid_flags = []
    for c in batch.columns:
        flat.append(jnp.asarray(c.data))
        valid_flags.append(c.valid is not None)
        if c.valid is not None:
            flat.append(jnp.asarray(c.valid))
    outs = _compact_fn(batch.num_columns, tuple(valid_flags), True, cap)(
        jnp.asarray(batch.live), *flat)
    cols = []
    i = 0
    for c, hv in zip(batch.columns, valid_flags):
        d = outs[i]
        i += 1
        v = None
        if hv:
            v = outs[i]
            i += 1
        cols.append(Column(c.type, d, v, c.dictionary))
    return ColumnBatch(batch.names, cols, outs[-1])


def partition_key_hashes(keys: Sequence[tuple]) -> np.ndarray:
    """Row -> uint64 key hash with NULL keys forced to 0.  The single
    routing hash shared by the shuffle sink and the adaptive routers: both
    must agree bit-for-bit on where a key lands (``h % n`` with null->0
    matches the legacy null->partition-0 placement for any n)."""
    datas = [jnp.asarray(d) for d, _ in keys]
    h = hash_combine(datas)
    null_mask = None
    for _, v in keys:
        if v is not None:
            nm = ~jnp.asarray(v)
            null_mask = nm if null_mask is None else (null_mask | nm)
    if null_mask is not None:
        h = jnp.where(null_mask, jnp.uint64(0), h)
    return np.asarray(h)


def partition_assignments(keys: Sequence[tuple], num_partitions: int) -> np.ndarray:
    """Row -> partition id by key hash (NULL keys -> partition 0)."""
    h = partition_key_hashes(keys)
    return (h % np.uint64(num_partitions)).astype(np.int32)
