"""Dynamic filtering: build-side join keys prune probe-side scans.

The local analogue of the reference's DynamicFilterService
(server/DynamicFilterService.java:105 + operator/DynamicFilterSourceOperator.
java:44): when a hash-join build side finishes, its key domain (min/max +
exact distinct set when small) becomes an extra predicate on the probe-side
table scan.  Because pipelines execute in dependency order (build before
probe), the filter is always complete before the probe scan starts — the
in-process equivalent of Trino's lazy-blocking DynamicFilter futures.

Only INNER and RIGHT joins attach filters: their unmatched probe rows are
dropped anyway, so pre-filtering cannot change results.  LEFT/FULL/SINGLE
joins and semi-join marks must see every probe row.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["DynamicFilterHolder", "MAX_DISTINCT_SET"]

MAX_DISTINCT_SET = 1 << 16  # keep an exact value set up to this many keys


class DynamicFilterHolder:
    """One build-side key column's domain, filled at JoinBuildSink.finish."""

    def __init__(self):
        self.ready = False
        self.empty = False  # build side had no rows: nothing can match
        self.vmin = None
        self.vmax = None
        self.values: Optional[np.ndarray] = None  # sorted exact set (or None)
        self.dict_values: Optional[set] = None  # for dictionary columns
        self.has_nan = False  # build had NaN keys (NaN joins NaN here)
        self.rows_pruned = 0  # observability: how many probe rows we dropped
        # device-resident domain, materialized on first probe_mask use (a
        # blocking fetch at fill time cost ~140ms/build over the tunnel and
        # bought nothing when every probe batch is device-pinned)
        self._pending_device = None

    def fill_device(self, data, valid, live,
                    dictionary: Optional[np.ndarray]) -> None:
        """Device-resident build keys: derive the domain with ONE jitted
        program + one small device_get instead of pulling the key column to
        host (the round-3 fill cost a full D2H of the build keys).  Builds
        small enough for an exact value set (<= MAX_DISTINCT_SET rows) pull
        the keys in one round trip and keep :meth:`fill`'s exact-set
        pruning; larger builds degrade to min/max range (+ dictionary
        presence for string keys)."""
        import jax

        n = int(data.shape[0])
        host_like = isinstance(data, np.ndarray) and (
            valid is None or isinstance(valid, np.ndarray)) and (
            live is None or isinstance(live, np.ndarray))
        if host_like or (n <= MAX_DISTINCT_SET and dictionary is None):
            from . import syncguard as SG

            data, valid, live = SG.fetch((data, valid, live),
                                         "dynfilter.build-domain")
            if live is not None:
                keep = np.asarray(live)
                data = np.asarray(data)[keep]
                valid = None if valid is None else np.asarray(valid)[keep]
            self.fill(np.asarray(data), valid, dictionary)
            return
        from .kernels import _device_domain

        dict_len = len(dictionary) if dictionary is not None else 0
        out = _device_domain(data, valid, live, dict_len)
        for a in jax.tree_util.tree_leaves(out):
            try:  # start the transfer; the sync happens lazily if ever
                a.copy_to_host_async()
            # tpulint: disable=error-taxonomy -- async-copy is a hint; backends without it keep the lazy fetch
            except Exception:
                pass
        self._pending_device = (out, dictionary)
        self.ready = True

    def _materialize(self) -> None:
        """Pull the device-computed domain to host (first probe_mask use)."""
        import jax

        out, dictionary = self._pending_device
        self._pending_device = None
        from . import syncguard as SG

        cnt, cnt_nonnan, vmin, vmax, presence = SG.fetch(
            out, "dynfilter.materialize")
        if int(cnt) == 0:
            self.empty = True
            return
        if dictionary is not None:
            self.dict_values = set(
                str(v) for v in dictionary[np.asarray(presence)])
        else:
            self.has_nan = int(cnt_nonnan) < int(cnt)
            if int(cnt_nonnan) > 0:
                self.vmin = vmin
                self.vmax = vmax

    def fill(self, data: np.ndarray, valid: Optional[np.ndarray],
             dictionary: Optional[np.ndarray]) -> None:
        data = np.asarray(data)
        if valid is not None:
            data = data[np.asarray(valid)]
        if data.size == 0:
            self.empty = True
            self.ready = True
            return
        if dictionary is not None:
            # dictionary codes are per-batch namespaces: keep the VALUES
            self.dict_values = set(str(v) for v in dictionary[np.unique(data)])
        else:
            uniq = np.unique(data)
            if np.issubdtype(uniq.dtype, np.floating):
                # NaN would poison the min/max range (x <= NaN is always
                # False); the engine's join kernels treat NaN = NaN as a
                # match, so remember it separately
                self.has_nan = bool(np.isnan(uniq).any())
                uniq = uniq[~np.isnan(uniq)]
                if uniq.size == 0:
                    if not self.has_nan:
                        self.empty = True
                    self.ready = True
                    return
            self.vmin = uniq[0]
            self.vmax = uniq[-1]
            if uniq.size <= MAX_DISTINCT_SET:
                self.values = uniq
        self.ready = True

    def probe_mask(self, data: np.ndarray, valid: Optional[np.ndarray],
                   dictionary: Optional[np.ndarray]) -> Optional[np.ndarray]:
        """Row mask of possibly-matching probe rows (None = keep all).
        NULL keys never match an equi-join, so they are dropped too."""
        if not self.ready:
            return None
        if self._pending_device is not None:
            self._materialize()
        data = np.asarray(data)
        if self.empty:
            return np.zeros(data.shape[0], bool)
        if dictionary is not None:
            if self.dict_values is None:
                return None
            code_ok = np.array([str(v) in self.dict_values for v in dictionary])
            mask = code_ok[data] if len(code_ok) else np.zeros(data.shape[0], bool)
        elif self.values is not None:
            pos = np.searchsorted(self.values, data)
            clipped = np.minimum(pos, self.values.size - 1)
            mask = self.values[clipped] == data
        elif self.vmin is not None:
            mask = (data >= self.vmin) & (data <= self.vmax)
        else:
            return None
        if self.has_nan and np.issubdtype(data.dtype, np.floating):
            mask = mask | np.isnan(data)
        if valid is not None:
            mask = mask & np.asarray(valid)
        return mask
