"""Task memory context + revocation: the HBM pool's spill trigger.

Wires ``spi/memory.py`` (MemoryPool/LocalMemoryContext — the
lib/trino-memory-context port) into the operators: every blocking operator
reserves its buffered DEVICE bytes as revocable memory; when a reservation
would exceed the HBM pool, the context asks the largest holders to revoke —
they evict their buffered batches to host RAM (``ColumnBatch.to_host``),
dropping the device references so XLA can free the buffers.  This is the
first spill tier of the reference's
``execution/MemoryRevokingScheduler.java:47`` +
``operator/aggregation/builder/SpillableHashAggregationBuilder.java`` design:
HBM -> host RAM (disk is a later tier).
"""

from __future__ import annotations

from typing import Optional, Protocol

import numpy as np

from ..spi.memory import AggregatedMemoryContext, ExceededMemoryLimitError, MemoryPool

__all__ = ["TaskMemoryContext", "device_nbytes", "batch_device_nbytes"]


def device_nbytes(arr) -> int:
    """Bytes an array holds on device (0 for host numpy)."""
    if arr is None or isinstance(arr, np.ndarray):
        return 0
    return int(np.dtype(arr.dtype).itemsize * arr.size)


def batch_device_nbytes(batch) -> int:
    n = 0
    for c in batch.columns:
        n += device_nbytes(c.data) + device_nbytes(c.valid)
    n += device_nbytes(batch.live)
    return n


class Revocable(Protocol):
    def revoke_memory(self) -> int:
        """Evict buffered device state to host; return bytes freed."""


class TaskMemoryContext:
    """Per-task accounting root: one HBM pool shared by the task's operators.

    ``update(op, nbytes)`` adjusts op's revocable reservation; on overflow it
    revokes from the largest other holders first (mirrors
    MemoryRevokingScheduler's TASK_THRESHOLD ordering), then from ``op``
    itself, and only then raises ExceededMemoryLimitError.
    """

    def __init__(self, hbm_limit_bytes: int, spill_to_disk_bytes: int = 0):
        self.pool = MemoryPool("hbm", hbm_limit_bytes)
        # per-operator HOST-buffer threshold for the disk spill tier
        # (0 = disabled; exec/spill.py)
        self.spill_to_disk_bytes = spill_to_disk_bytes
        self.root = AggregatedMemoryContext(pool=self.pool, revocable=True)
        self._locals: dict[int, object] = {}
        self._ops: dict[int, Revocable] = {}

    def register(self, op) -> None:
        key = id(op)
        if key not in self._locals:
            self._locals[key] = self.root.new_local(type(op).__name__)
            self._ops[key] = op

    def update(self, op, nbytes: int) -> None:
        """Set op's revocable reservation to ``nbytes``, revoking other
        holders (largest first) and finally op itself when the pool is full.

        Revocable reservations never throw in MemoryPool.reserve (matching
        the reference), so capacity is checked here and spills are triggered
        synchronously — the single-threaded stand-in for
        MemoryRevokingScheduler's listener."""
        key = id(op)
        self.register(op)
        ctx = self._locals[key]
        delta = nbytes - ctx.reserved
        if delta > 0 and self.pool.free_bytes < delta:
            holders = sorted(
                ((k, c) for k, c in self._locals.items()
                 if c.reserved > 0 and k != key),
                key=lambda kv: kv[1].reserved, reverse=True)
            for k, c in holders:
                freed = self._ops[k].revoke_memory()
                if freed:
                    c.set_bytes(max(0, c.reserved - freed))
                if self.pool.free_bytes >= delta:
                    break
            if self.pool.free_bytes < delta:
                # last resort: the requester evicts its own buffer
                self._ops[key].revoke_memory()
                nbytes = batch_device_residual(self._ops[key])
                delta = nbytes - ctx.reserved
                if delta > 0 and self.pool.free_bytes < delta:
                    raise ExceededMemoryLimitError(
                        self.pool.name, delta, self.pool.max_bytes)
        ctx.set_bytes(nbytes)

    def reserved_bytes(self) -> int:
        return self.pool.reserved + self.pool.reserved_revocable


def batch_device_residual(op) -> int:
    batches = getattr(op, "_batches", None)
    if not batches:
        return 0
    return sum(batch_device_nbytes(b) for b in batches)
