"""Asynchronous scan ingest: split prefetch, batch coalescing, device staging.

The synchronous scan path serializes three things that have no business
serializing: host-side split decoding (connector ``get_next_batch``),
host->device transfer, and device compute.  This module supplies the three
pieces ScanOperator composes to overlap them — the ingest-side counterpart
of Trino's split pipeline (ScanFilterAndProjectOperator's lazy pages +
MergePages coalescing; reference: operator/ScanFilterAndProjectOperator.java:68,
operator/project/MergePages.java:38, split prefetch via
ConnectorSplitSource.getNextBatch futures):

- :class:`PrefetchingPageSource` drains connector splits on a bounded
  background thread pool into a memory-accounted queue.  Split order is
  preserved (batches of split k always precede batches of split k+1),
  backpressure parks producers when the queue exceeds its byte/depth budget,
  and ``close()`` aborts in-flight reads so a satisfied LIMIT stops paying
  for splits nobody will consume.  A crash on a prefetch thread is re-raised
  on the consumer.
- :class:`BatchCoalescer` merges small scan batches up to a target
  power-of-two bucket before staging, writing every part into ONE
  preallocated bucket-sized buffer per column (no per-column concatenates),
  so jitted programs run with full lanes instead of padding half-empty
  buckets.
- :class:`DeviceStager` double-buffers host->device transfer: ScanOperator
  stages batch N+1 with ``jax.device_put`` (async dispatch) while the
  downstream operators compute on batch N, so the transfer rides under
  compute instead of in front of it.

Every knob reads from the environment once per source (see
:class:`IngestConfig`); ``TRINO_TPU_PREFETCH=0`` disables the whole pipeline
and ScanOperator falls back to the bit-for-bit synchronous path.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from ..spi.batch import (Column, ColumnBatch, encoded_exec, maybe_rle,
                         round_up_pow2, unify_dictionaries)
from ..spi.errors import GENERIC_INTERNAL_ERROR, TrinoError
from .stats import EncodingStats, ScanIngestStats

__all__ = [
    "IngestConfig",
    "PrefetchingPageSource",
    "BatchCoalescer",
    "DeviceStager",
    "coalesce_pad",
]


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


@dataclass(frozen=True)
class IngestConfig:
    """Scan-ingest knobs (one env read per scan, so tests can flip them)."""

    enabled: bool = True            # TRINO_TPU_PREFETCH
    threads: int = 2                # TRINO_TPU_PREFETCH_THREADS
    queue_depth: int = 8            # TRINO_TPU_PREFETCH_QUEUE_DEPTH (batches)
    queue_bytes: int = 256 << 20    # TRINO_TPU_PREFETCH_QUEUE_BYTES
    coalesce_rows: int = 1 << 16    # TRINO_TPU_COALESCE_TARGET_ROWS
    stage_device: bool = True       # TRINO_TPU_STAGE_DEVICE

    @staticmethod
    def default_threads() -> int:
        """Prefetch decode threads auto-tuned from the host: cpu_count - 1
        (one core stays with the consumer/dispatch thread), capped at 4 —
        the decode is memory-bandwidth-bound past that.  A single-core host
        gets 0: an extra thread there only adds GIL contention, so the scan
        runs synchronously instead."""
        return min(4, max(0, (os.cpu_count() or 1) - 1))

    @staticmethod
    def from_env() -> "IngestConfig":
        threads = _env_int("TRINO_TPU_PREFETCH_THREADS", -1)
        explicit_on = os.environ.get("TRINO_TPU_PREFETCH") == "1"
        if threads < 0:  # unset: auto-tune from the host core count
            threads = IngestConfig.default_threads()
            if explicit_on:  # explicit opt-in overrides the auto-disable
                threads = max(1, threads)
        return IngestConfig(
            # threads == 0 (explicit, or auto on single-core) disables the
            # async path entirely rather than spawning useless workers
            enabled=(os.environ.get("TRINO_TPU_PREFETCH", "1") != "0"
                     and threads > 0),
            threads=max(1, threads),
            queue_depth=max(1, _env_int("TRINO_TPU_PREFETCH_QUEUE_DEPTH", 8)),
            queue_bytes=max(1, _env_int(
                "TRINO_TPU_PREFETCH_QUEUE_BYTES", 256 << 20)),
            coalesce_rows=max(1, _env_int(
                "TRINO_TPU_COALESCE_TARGET_ROWS", 1 << 16)),
            stage_device=os.environ.get("TRINO_TPU_STAGE_DEVICE", "1") != "0",
        )


class PrefetchingPageSource:
    """Order-preserving multi-split prefetcher with a bounded queue.

    Worker threads claim splits in order and append decoded batches to a
    per-split buffer; the consumer drains buffers strictly in split order, so
    downstream row order matches the synchronous scan exactly.  Backpressure:
    producers park while the queue is over its byte or depth budget, except
    the producer of the consumer's current split while that split's buffer is
    empty (a starved consumer can always make progress — no deadlock with any
    budget >= 1 batch).
    """

    def __init__(self, connector, splits: Sequence, columns: Sequence[str],
                 constraint=None, config: Optional[IngestConfig] = None,
                 stats: Optional[ScanIngestStats] = None,
                 limit_rows: Optional[int] = None):
        self.connector = connector
        self.splits = list(splits)
        self.columns = list(columns)
        self.constraint = constraint
        self.cfg = config if config is not None else IngestConfig.from_env()
        self.stats = stats if stats is not None else ScanIngestStats()
        self.stats.prefetch_enabled = True
        self.limit_rows = limit_rows
        self._cv = threading.Condition()
        self._buffers: list[deque] = [deque() for _ in self.splits]
        self._done = [False] * len(self.splits)
        self._next_claim = 0   # next split a worker picks up (in order)
        self._consume = 0      # split the consumer is draining
        self._inflight = 0     # splits claimed but not finished (limit gate)
        self._queued_bytes = 0
        self._queued_batches = 0
        self._queued_rows = 0
        self._error: Optional[BaseException] = None
        self._closed = False
        n = min(self.cfg.threads, max(1, len(self.splits)))
        self._threads = [
            threading.Thread(target=self._work, daemon=True,
                             name=f"scan-prefetch-{i}")
            for i in range(n)
        ]
        for t in self._threads:
            t.start()

    # -- producer side -----------------------------------------------------
    def _open_source(self, split):
        # kwarg only when constrained: wrapper connectors with the bare
        # (split, columns) signature keep working (same contract as the
        # synchronous scan)
        if self.constraint is not None:
            return self.connector.create_page_source(
                split, self.columns, constraint=self.constraint)
        return self.connector.create_page_source(split, self.columns)

    def _over_budget(self) -> bool:
        return (self._queued_bytes >= self.cfg.queue_bytes
                or self._queued_batches >= self.cfg.queue_depth)

    def _work(self) -> None:
        try:
            while True:
                with self._cv:
                    # a pushed-down LIMIT makes split claiming lazy: one
                    # split in flight at a time, and none while the queue
                    # already holds enough raw rows to satisfy the limit.
                    # Filters may drop rows, so this only PAUSES claiming —
                    # the consumer draining the queue resumes it (a pause,
                    # never a stop: correctness does not depend on it)
                    while (self.limit_rows is not None
                           and (self._inflight >= 1
                                or self._queued_rows >= self.limit_rows)
                           and self._next_claim < len(self.splits)
                           and not self._closed and self._error is None):
                        self._cv.wait(0.05)
                    if self._closed or self._error is not None:
                        return
                    if self._next_claim >= len(self.splits):
                        return
                    idx = self._next_claim
                    self._next_claim += 1
                    self._inflight += 1
                    self.stats.splits_opened += 1
                src = self._open_source(self.splits[idx])
                try:
                    while True:
                        with self._cv:
                            # park while over budget — UNLESS the consumer is
                            # starved waiting on THIS split (exemption keeps
                            # the in-order drain progressing: no deadlock for
                            # any budget >= 1 batch)
                            while (self._over_budget()
                                   and not (idx == self._consume
                                            and not self._buffers[idx])
                                   and not self._closed
                                   and self._error is None):
                                self._cv.wait(0.05)
                            if self._closed or self._error is not None:
                                return
                        if src.is_finished():
                            break
                        t0 = time.perf_counter()
                        b = src.get_next_batch()
                        dt = time.perf_counter() - t0
                        with self._cv:
                            self.stats.source_read_s += dt
                            if b is not None:
                                self._buffers[idx].append(b)
                                self._queued_bytes += b.nbytes
                                self._queued_batches += 1
                                self._queued_rows += b.num_rows
                                s = self.stats
                                s.queue_depth_max = max(
                                    s.queue_depth_max, self._queued_batches)
                            self._cv.notify_all()
                finally:
                    src.close()
                with self._cv:
                    self._done[idx] = True
                    self._inflight -= 1
                    self._cv.notify_all()
        except BaseException as e:  # noqa: BLE001 — re-raised on the consumer
            with self._cv:
                if self._error is None:
                    self._error = e
                self._cv.notify_all()

    # -- consumer side -----------------------------------------------------
    def _advance(self) -> None:
        while (self._consume < len(self.splits)
               and self._done[self._consume]
               and not self._buffers[self._consume]):
            self._consume += 1

    def get_next_batch(self) -> Optional[ColumnBatch]:
        """Next batch in split order; blocks while prefetch is behind.
        Returns None when every split is drained (or after close)."""
        with self._cv:
            while True:
                if self._error is not None:
                    err = self._error
                    raise TrinoError(
                        GENERIC_INTERNAL_ERROR,
                        f"scan prefetch thread failed: {err}") from err
                if self._closed:
                    return None
                self._advance()
                if self._consume >= len(self.splits):
                    return None
                buf = self._buffers[self._consume]
                if buf:
                    b = buf.popleft()
                    self._queued_bytes -= b.nbytes
                    self._queued_batches -= 1
                    self._queued_rows -= b.num_rows
                    s = self.stats
                    s.queue_depth_sum += self._queued_batches + 1
                    s.queue_samples += 1
                    s.observe_batch(b.nbytes, b.num_rows)
                    self._cv.notify_all()
                    return b
                t0 = time.perf_counter()
                self._cv.wait(0.05)
                self.stats.consumer_wait_s += time.perf_counter() - t0

    def is_finished(self) -> bool:
        with self._cv:
            if self._closed or self._error is not None:
                return True
            self._advance()
            return self._consume >= len(self.splits)

    def close(self) -> None:
        """Early close (satisfied LIMIT / downstream done): producers abort
        at the next check and unclaimed splits are never opened."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()


def coalesce_pad(parts: Sequence[ColumnBatch],
                 min_rows: int = 8) -> ColumnBatch:
    """Merge dense host batches into ONE batch padded to the power-of-two
    bucket of the total, writing each part into a preallocated bucket-sized
    buffer per column (single allocation + one memcpy pass — replaces the
    per-batch per-column concatenates of pad_to_bucket on this path).
    Dictionary columns are unified onto one shared code space first."""
    assert parts, "coalesce_pad of no batches"
    names = parts[0].names
    total = sum(p.num_rows for p in parts)
    cap = round_up_pow2(total, min_rows)
    out_cols = []
    for i in range(len(names)):
        cs = [p.columns[i] for p in parts]
        if cs[0].type.is_dictionary_encoded:
            cs = unify_dictionaries(cs)
        data = np.zeros(cap, dtype=np.asarray(cs[0].data).dtype)
        valid = None
        if any(c.valid is not None for c in cs):
            valid = np.zeros(cap, dtype=np.bool_)
        pos = 0
        for c in cs:
            n = len(c)
            data[pos:pos + n] = np.asarray(c.data)
            if valid is not None:
                if c.valid is None:
                    valid[pos:pos + n] = True
                else:
                    valid[pos:pos + n] = np.asarray(c.valid)
            pos += n
        out_cols.append(Column(cs[0].type, data, valid, cs[0].dictionary))
    live = None
    if cap != total:
        live = np.zeros(cap, dtype=np.bool_)
        live[:total] = True
    return ColumnBatch(list(names), out_cols, live)


class BatchCoalescer:
    """Accumulate small dense host batches and emit bucket-padded merges.

    ``add`` buffers; once the buffered rows reach ``target_rows`` (or the
    caller flushes at end of input) the parts merge via :func:`coalesce_pad`.
    Batches that are already bucket-shaped (``live`` set — device-pinned
    tables) or device-resident must NOT enter the coalescer: pulling them to
    host would cost more than full lanes save (callers pass those through).
    """

    def __init__(self, target_rows: int,
                 stats: Optional[ScanIngestStats] = None):
        self.target_rows = target_rows
        self.stats = stats
        self._parts: list[ColumnBatch] = []
        self._rows = 0

    @property
    def buffered_rows(self) -> int:
        return self._rows

    def add(self, batch: ColumnBatch) -> None:
        assert batch.live is None, "coalescer input must be dense"
        if batch.num_rows:
            self._parts.append(batch)
            self._rows += batch.num_rows

    def ready(self) -> bool:
        return self._rows >= self.target_rows

    def flush(self) -> Optional[ColumnBatch]:
        """Merge-and-pad everything buffered (None when empty)."""
        if not self._parts:
            return None
        parts, self._parts, self._rows = self._parts, [], 0
        if self.stats is not None:
            self.stats.coalesced_batches += 1
            self.stats.coalesced_rows += sum(p.num_rows for p in parts)
        if len(parts) == 1 and round_up_pow2(
                parts[0].num_rows) == parts[0].num_rows:
            return parts[0]  # already exactly bucket-shaped: nothing to do
        return coalesce_pad(parts)


def encode_column(i: int, c: Column, lazy_channels,
                  enc_stats: Optional[EncodingStats] = None
                  ) -> Optional[Column]:
    """Compressed execution: RLE-collapse a constant column, or LAZY-wrap a
    channel the planner proved the filter never touches.  Returns None when
    the column should be handled the legacy way (staged / passed through)."""
    rle = maybe_rle(c)
    if rle is not c:
        # constant run: ONE host scalar represents the whole column; the
        # expand (if any) happens device-side via kernels.rle_fill
        if enc_stats is not None:
            enc_stats.bytes_saved += rle.flat_nbytes - rle.nbytes
        return rle
    if i in lazy_channels:
        data, valid = c.data, c.valid

        def thunk(data=data, valid=valid):
            return data, valid

        if enc_stats is not None:
            enc_stats.lazy_columns += 1
            enc_stats.lazy_skipped_bytes += c.nbytes
        return Column.lazy(c.type, len(c), thunk, c.dictionary,
                           nbytes_hint=c.nbytes)
    return None


def encode_scan_batch(batch: ColumnBatch, lazy_channels,
                      enc_stats: Optional[EncodingStats] = None
                      ) -> ColumnBatch:
    """Compressed-execution pass for the synchronous scan path (no async
    ingest, so batches never reach DeviceStager).  Host batches only —
    device-pinned batches (live mask set) pass through untouched."""
    if (not batch.columns or batch.live is not None
            or not isinstance(batch.columns[0].data, np.ndarray)):
        return batch
    any_rle = False
    changed = False
    cols = []
    for i, c in enumerate(batch.columns):
        enc = encode_column(i, c, lazy_channels, enc_stats)
        if enc is not None:
            any_rle = any_rle or enc.encoding == "RLE"
            changed = True
            cols.append(enc)
        else:
            cols.append(c)
    if not changed:
        return batch
    if any_rle and enc_stats is not None:
        enc_stats.rle_batches += 1
    return ColumnBatch(batch.names, cols, batch.live)


class DeviceStager:
    """Double-buffered host->device staging.

    ``stage`` dispatches ``jax.device_put`` for every array of a padded host
    batch and returns immediately with the device handles — the transfer
    runs asynchronously, so staging batch N+1 before returning batch N to
    the driver overlaps its upload with downstream compute on N.  Batches
    that already live on device pass through untouched."""

    def __init__(self, stats: Optional[ScanIngestStats] = None,
                 lazy_channels=None,
                 enc_stats: Optional[EncodingStats] = None):
        self.stats = stats
        # compressed execution (plan_lazy_scan): these channels defer their
        # transfer behind a thunk instead of staging eagerly
        self.lazy_channels = frozenset(lazy_channels or ())
        self.enc_stats = enc_stats

    def _stage_encoded(self, i: int, c: Column) -> Optional[Column]:
        """RLE-collapse or LAZY-wrap one column instead of staging it; None
        means stage eagerly (the legacy device_put)."""
        return encode_column(i, c, self.lazy_channels, self.enc_stats)

    def stage(self, batch: ColumnBatch) -> ColumnBatch:
        if not batch.columns or not isinstance(
                batch.columns[0].data, np.ndarray):
            return batch
        import jax

        t0 = time.perf_counter()
        encoded = encoded_exec()
        any_rle = False
        cols = []
        for i, c in enumerate(batch.columns):
            if encoded:
                enc = self._stage_encoded(i, c)
                if enc is not None:
                    any_rle = any_rle or enc.encoding == "RLE"
                    cols.append(enc)
                    continue
            data = jax.device_put(c.data)
            valid = None if c.valid is None else jax.device_put(c.valid)
            cols.append(Column(c.type, data, valid, c.dictionary))
        if any_rle and self.enc_stats is not None:
            self.enc_stats.rle_batches += 1
        live = batch.live
        if live is not None:
            live = jax.device_put(live)
        if self.stats is not None:
            self.stats.stage_s += time.perf_counter() - t0
            self.stats.staged_batches += 1
        return ColumnBatch(batch.names, cols, live)
