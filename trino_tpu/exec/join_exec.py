"""Device-resident hash-join execution programs.

Round-4 rework of the join hot path (reference: operator/join/
LookupJoinOperator.java:37, HashBuilderOperator.java:57, PagesHash).  The
round-3 engine pulled every (probe_idx, build_idx) match pair to the host
(`jax.device_get` of megarow int64 arrays through a 10-80 MB/s tunnel) and
re-uploaded them for gathers; this module keeps the whole probe on device:

- ``build_table``: ONE jitted program hashes + sorts the build keys
  (``hash_combine`` + argsort on chip); one 2-scalar device_get fetches
  (has_null_key, live_rows) for planner-visible semantics.
- ``probe_ranges``: ONE jitted program computes candidate ranges via binary
  search in the sorted hash; ONE scalar sync fetches the total candidate
  count (needed to pick the static expansion bucket — the only data-
  dependent shape in the join).
- ``run_pairs``: ONE jitted program per (join shape, residual, bucket)
  expands candidates, verifies key equality exactly (hash candidates ->
  per-key compare, NaN=NaN), evaluates the residual predicate, gathers ALL
  output columns at the matched pairs, and computes per-probe matched flags
  for LEFT/SINGLE and the semi-join mark — outputs stay on device as a
  ``live``-masked batch.

Total blocking host interaction per probe batch: one scalar sync.
"""

from __future__ import annotations

import threading
from functools import lru_cache
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.expr import compile_expression
from ..sql.ir import RowExpression
from . import kernels as K

__all__ = ["DeviceJoinTable", "build_table", "probe_ranges", "run_pairs"]

_SENT_BUILD = 0xFFFFFFFFFFFFFFFF  # build rows with NULL keys / dead rows
_SENT_PROBE = 0xFFFFFFFFFFFFFFFE  # probe rows with NULL keys


class DeviceJoinTable:
    """Sorted-hash build side, all arrays device-resident."""

    __slots__ = ("sorted_hash", "perm", "key_datas", "has_null_key",
                 "num_rows", "live_rows")

    def __init__(self, sorted_hash, perm, key_datas,
                 has_null_key: bool, num_rows: int, live_rows: int):
        self.sorted_hash = sorted_hash
        self.perm = perm
        self.key_datas = key_datas  # unsorted, for exact verify
        self.has_null_key = has_null_key  # among LIVE rows
        self.num_rows = num_rows  # physical slots (incl. dead padding)
        self.live_rows = live_rows


@lru_cache(maxsize=None)
def _build_fn(num_keys: int, has_valid: tuple, has_live: bool):
    @jax.jit
    def fn(*flat):
        i = 0
        datas, valids = [], []
        for k in range(num_keys):
            datas.append(flat[i])
            i += 1
            if has_valid[k]:
                valids.append(flat[i])
                i += 1
            else:
                valids.append(None)
        live = flat[i] if has_live else None
        h = K.hash_combine(datas)
        null_mask = None
        for v in valids:
            if v is not None:
                nm = ~v
                null_mask = nm if null_mask is None else (null_mask | nm)
        n = datas[0].shape[0]
        live_rows = (jnp.asarray(n, jnp.int64) if live is None
                     else jnp.sum(live))
        if null_mask is not None:
            has_null = jnp.any(null_mask if live is None
                               else (null_mask & live))
            h = jnp.where(null_mask, jnp.uint64(_SENT_BUILD), h)
        else:
            has_null = jnp.asarray(False)
        if live is not None:
            h = jnp.where(live, h, jnp.uint64(_SENT_BUILD))
        perm = jnp.argsort(h)
        return h[perm], perm, has_null, live_rows

    return fn


def build_table(keys: Sequence[tuple], live=None,
                num_rows: Optional[int] = None) -> DeviceJoinTable:
    """keys: [(data, valid|None), ...]; ``live`` masks dead (padded) build
    rows — they never match and don't count toward live_rows/has_null."""
    if not keys:  # cross join: every probe row pairs with every live row
        n = int(num_rows or 0)
        lr = n
        if live is not None:
            lr = int(np.asarray(jnp.sum(jnp.asarray(live))))
        return DeviceJoinTable(None, None, [], False, n, lr)
    has_valid = tuple(v is not None for _, v in keys)
    flat: list = []
    datas = []
    for (d, v), hv in zip(keys, has_valid):
        d = jnp.asarray(d)
        datas.append(d)
        flat.append(d)
        if hv:
            flat.append(jnp.asarray(v))
    if live is not None:
        flat.append(jnp.asarray(live))
    sh, perm, has_null, live_rows = _build_fn(
        len(keys), has_valid, live is not None)(*flat)
    # one round trip for both planner-visible scalars
    has_null_h, live_rows_h = jax.device_get((has_null, live_rows))
    return DeviceJoinTable(sh, perm, datas, bool(has_null_h),
                           int(datas[0].shape[0]), int(live_rows_h))


@lru_cache(maxsize=None)
def _ranges_fn(num_keys: int, has_valid: tuple, has_live: bool,
               has_remap: tuple):
    @jax.jit
    def fn(sorted_hash, *flat):
        i = 0
        datas, valids = [], []
        for k in range(num_keys):
            d = flat[i]
            i += 1
            if has_remap[k]:
                d = flat[i][d]  # dictionary remap table gather
                i += 1
            datas.append(d)
            if has_valid[k]:
                valids.append(flat[i])
                i += 1
            else:
                valids.append(None)
        live = flat[i] if has_live else None
        h = K.hash_combine(datas)
        pnull = None
        for k, v in enumerate(valids):
            nm = ~v if v is not None else None
            if has_remap[k]:
                # remapped code -1 = value absent from the build dictionary:
                # cannot match (but is NOT a null probe for null-aware marks)
                miss = datas[k] < 0
                nm = miss if nm is None else (nm | miss)
            if nm is not None:
                pnull = nm if pnull is None else (pnull | nm)
        if pnull is not None:
            h = jnp.where(pnull, jnp.uint64(_SENT_PROBE), h)
        lo = K.searchsorted(sorted_hash, h, side="left")
        hi = K.searchsorted(sorted_hash, h, side="right")
        counts = hi - lo
        if pnull is not None:
            counts = jnp.where(pnull, 0, counts)
        if live is not None:
            counts = jnp.where(live, counts, 0)
        # the build sentinel region (null/dead rows) must never match, and
        # null probes must not hit it
        counts = jnp.where(h >= jnp.uint64(_SENT_PROBE), 0, counts)
        return lo, counts, jnp.sum(counts)

    return fn


def probe_ranges(table: DeviceJoinTable, probe_keys: Sequence[tuple],
                 remaps: Sequence[Optional[np.ndarray]], live=None):
    """probe_keys: [(data, valid|None), ...]; ``remaps[k]`` an optional
    host int32 table translating probe dictionary codes into the build code
    space (-1 = value absent).  Returns (lo, counts, total:int) with
    lo/counts on device — ONE host scalar sync."""
    has_valid = tuple(v is not None for _, v in probe_keys)
    has_remap = tuple(r is not None for r in remaps)
    flat: list = [table.sorted_hash]
    for (d, v), r in zip(probe_keys, remaps):
        flat.append(jnp.asarray(d))
        if r is not None:
            flat.append(jnp.asarray(r))
        if v is not None:
            flat.append(jnp.asarray(v))
    if live is not None:
        flat.append(jnp.asarray(live))
    lo, counts, total = _ranges_fn(
        len(probe_keys), has_valid, live is not None, has_remap)(*flat)
    return lo, counts, int(total)


# ---------------------------------------------------------------------------
# pair expansion + verify + residual + output gather: one program

_PAIR_CACHE: dict = {}
_PAIR_LOCK = threading.Lock()
_PAIR_CACHE_MAX = 1024

# dictionary identity tokens: monotonically assigned, NEVER recycled while
# the dictionary object is alive (checked via weakref), so a cache key built
# from tokens cannot alias a new dictionary at a recycled id() — which made
# eviction unsafe in the r4 id()-keyed design (advisor r4 medium).  With
# stable tokens the LRU eviction below is safe and nothing needs pinning.
_DICT_TOKENS: dict[int, tuple] = {}  # id(d) -> (weakref|strong-thunk, token)
_DICT_SEQ = 0


def _dict_token(d):
    global _DICT_SEQ
    if d is None:
        return None
    import weakref

    i = id(d)
    ent = _DICT_TOKENS.get(i)
    if ent is not None and ent[0]() is d:
        return ent[1]
    _DICT_SEQ += 1
    tok = _DICT_SEQ
    try:
        # the collection callback fires before the id can be reused, so it
        # cannot delete a newer entry — keeps the table bounded by LIVE dicts
        ref = weakref.ref(d, lambda _r, _i=i: _DICT_TOKENS.pop(_i, None))
    except TypeError:  # not weakrefable: keep it alive so the id can't recycle
        ref = (lambda _d=d: _d)
    _DICT_TOKENS[i] = (ref, tok)
    return tok


def _make_pair_fn(cap: int, num_keys: int, has_pvalid: tuple,
                  has_remap: tuple, pair_types, pair_dicts,
                  n_probe_cols: int, n_build_cols: int,
                  pcol_has_valid: tuple, bcol_has_valid: tuple,
                  residual: Optional[RowExpression],
                  need_matched: bool, semi: Optional[tuple]):
    """Build the pair program.  Flat operand order:
    lo, counts, total, perm,
    per probe key: data [remap] [valid],
    per probe col: data [valid],
    per build col: data [valid],
    build key datas.

    ``semi``: None for a regular join; (null_aware, has_null_build,
    build_nonempty) for the semi-join mark variant (outputs (mark, valid)
    instead of gathered pair columns)."""
    res_fn = (compile_expression(residual, list(pair_types), list(pair_dicts))
              if residual is not None else None)

    def fn(lo, counts, total, perm, *flat):
        i = 0
        pkeys, pkvalids = [], []
        for k in range(num_keys):
            d = flat[i]
            i += 1
            if has_remap[k]:
                d = flat[i][d]
                i += 1
            pkeys.append(d)
            if has_pvalid[k]:
                pkvalids.append(flat[i])
                i += 1
            else:
                pkvalids.append(None)
        pcols = []
        for c in range(n_probe_cols):
            d = flat[i]
            i += 1
            v = None
            if pcol_has_valid[c]:
                v = flat[i]
                i += 1
            pcols.append((d, v))
        bcols = []
        for c in range(n_build_cols):
            d = flat[i]
            i += 1
            v = None
            if bcol_has_valid[c]:
                v = flat[i]
                i += 1
            bcols.append((d, v))
        bkeys = list(flat[i:i + num_keys])

        n_probe = pkeys[0].shape[0] if pkeys else (
            pcols[0][0].shape[0] if pcols else 1)
        nb = perm.shape[0]
        ends = jnp.cumsum(counts)
        starts = ends - counts
        slot = jnp.arange(cap)
        probe_id = jnp.clip(
            K.searchsorted(ends, slot, side="right"), 0, n_probe - 1)
        within = slot - starts[probe_id]
        build_pos = lo[probe_id] + within
        build_id = perm[jnp.clip(build_pos, 0, nb - 1)]
        ok = slot < total
        for pk, bk in zip(pkeys, bkeys):
            ok = ok & ~K._neq(pk[probe_id], bk[build_id])

        pairs = None
        if semi is None or res_fn is not None:
            pairs = [(d[probe_id], None if v is None else v[probe_id])
                     for d, v in pcols]
            pairs += [(d[build_id], None if v is None else v[build_id])
                      for d, v in bcols]
        if res_fn is not None:
            rd, rv = res_fn(pairs)
            rmask = rd if rv is None else (rd & rv)
            if getattr(rmask, "ndim", 1) == 0:
                rmask = jnp.broadcast_to(rmask, (cap,))
            ok = ok & rmask

        matched = None
        max_per_probe = None
        if need_matched or semi is not None:
            # per-probe match count: pairs are sorted by probe_id, so the
            # count is a prefix-sum difference at segment boundaries
            # (scatters serialize on TPU; this is all gathers)
            cs = jnp.cumsum(ok.astype(jnp.int64))
            pr = jnp.arange(n_probe)
            pend = K.searchsorted(probe_id, pr, side="right")
            pstart = K.searchsorted(probe_id, pr, side="left")
            hi2 = cs[jnp.maximum(pend - 1, 0)]
            lo2 = jnp.where(pstart > 0, cs[jnp.maximum(pstart - 1, 0)],
                            jnp.zeros((), jnp.int64))
            cnt = jnp.where(pend > pstart, hi2 - lo2, 0)
            matched = cnt > 0
            max_per_probe = jnp.max(cnt)

        if semi is not None:
            # three-valued NOT IN: a non-match is UNKNOWN (NULL mark) when
            # the probe key is NULL or the build side contains a NULL key;
            # IN over the empty set is FALSE even for NULL probes
            null_aware, has_null_build, build_nonempty = semi
            mark_valid = None
            if null_aware and build_nonempty:
                if has_null_build:
                    unknown = ~matched
                else:
                    null_probe = jnp.zeros((n_probe,), jnp.bool_)
                    for v in pkvalids:
                        if v is not None:
                            null_probe = null_probe | ~v
                    unknown = ~matched & null_probe
                mark_valid = ~unknown
            return None, ok, matched, max_per_probe, (matched, mark_valid)
        return pairs, ok, matched, max_per_probe, build_id

    return jax.jit(fn)


def run_pairs(table: DeviceJoinTable, lo, counts, total: int,
              probe_keys, remaps, probe_cols, build_cols,
              pair_types, pair_dicts,
              residual: Optional[RowExpression],
              need_matched: bool, semi: Optional[tuple] = None):
    """Execute the pair program.  Returns (pair_cols|None, pair_live,
    matched|None, max_per_probe|None, mark|None) — ALL device arrays, zero
    host syncs.  ``pair_cols`` is [(data, valid|None), ...] over probe cols
    then build cols, gathered at the matched pairs.  The 5th element is the
    device build_id per pair slot for a regular join, or the (data, valid)
    semi-join mark when ``semi`` is set."""
    cap = K.bucket(max(total, 1))
    has_pvalid = tuple(v is not None for _, v in probe_keys)
    has_remap = tuple(r is not None for r in remaps)
    pcol_has_valid = tuple(v is not None for _, v in probe_cols)
    bcol_has_valid = tuple(v is not None for _, v in build_cols)
    with _PAIR_LOCK:
        key = (cap, len(probe_keys), has_pvalid, has_remap,
               tuple(str(t) for t in pair_types),
               tuple(_dict_token(d) for d in pair_dicts),
               len(probe_cols), len(build_cols), pcol_has_valid,
               bcol_has_valid, residual, need_matched, semi)
        prog = _PAIR_CACHE.pop(key, None)
        if prog is not None:  # re-insert: dict ordering = LRU order
            _PAIR_CACHE[key] = prog
    if prog is None:
        prog = _make_pair_fn(cap, len(probe_keys), has_pvalid, has_remap,
                             list(pair_types), list(pair_dicts),
                             len(probe_cols), len(build_cols),
                             pcol_has_valid, bcol_has_valid,
                             residual, need_matched, semi)
        with _PAIR_LOCK:
            prog = _PAIR_CACHE.setdefault(key, prog)
            while len(_PAIR_CACHE) > _PAIR_CACHE_MAX:
                _PAIR_CACHE.pop(next(iter(_PAIR_CACHE)))

    flat: list = []
    for (d, v), r in zip(probe_keys, remaps):
        flat.append(jnp.asarray(d))
        if r is not None:
            flat.append(jnp.asarray(r))
        if v is not None:
            flat.append(jnp.asarray(v))
    for d, v in probe_cols:
        flat.append(jnp.asarray(d))
        if v is not None:
            flat.append(jnp.asarray(v))
    for d, v in build_cols:
        flat.append(jnp.asarray(d))
        if v is not None:
            flat.append(jnp.asarray(v))
    flat.extend(table.key_datas)
    pairs, ok, matched, maxc, extra = prog(
        lo, counts, jnp.asarray(total, jnp.int64), table.perm, *flat)
    return pairs, ok, matched, maxc, extra
