"""Device-resident hash-join execution programs.

Round-4 rework of the join hot path (reference: operator/join/
LookupJoinOperator.java:37, HashBuilderOperator.java:57, PagesHash).  The
round-3 engine pulled every (probe_idx, build_idx) match pair to the host
(`jax.device_get` of megarow int64 arrays through a 10-80 MB/s tunnel) and
re-uploaded them for gathers; this module keeps the whole probe on device:

- ``build_table``: ONE jitted program hashes + sorts the build keys
  (``hash_combine`` + argsort on chip); one 2-scalar device_get fetches
  (has_null_key, live_rows) for planner-visible semantics.
- ``probe_ranges``: ONE jitted program computes candidate ranges via binary
  search in the sorted hash; ONE scalar sync fetches the total candidate
  count (needed to pick the static expansion bucket — the only data-
  dependent shape in the join).
- ``run_pairs``: ONE jitted program per (join shape, residual, bucket)
  expands candidates, verifies key equality exactly (hash candidates ->
  per-key compare, NaN=NaN), evaluates the residual predicate, gathers ALL
  output columns at the matched pairs, and computes per-probe matched flags
  for LEFT/SINGLE and the semi-join mark — outputs stay on device as a
  ``live``-masked batch.

Total blocking host interaction per probe batch: one scalar sync.
"""

from __future__ import annotations

import threading
from ..caching.executable_cache import jit_memo
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.expr import compile_expression
from ..sql.ir import RowExpression
from . import kernels as K
from . import syncguard as SG

__all__ = ["DeviceJoinTable", "JoinHashTable", "build_table", "probe_ranges",
           "probe_ranges_device", "run_pairs", "run_unique",
           "ExpandPlanner", "OverflowQueue", "plan_unique_cap", "key_input"]

_SENT_BUILD = 0xFFFFFFFFFFFFFFFF  # build rows with NULL keys / dead rows
_SENT_PROBE = 0xFFFFFFFFFFFFFFFE  # probe rows with NULL keys


def key_input(col):
    """Device-ready key data for a probe/build column under compressed
    execution: an RLE run expands device-side from its ONE stored scalar
    (kernels.rle_fill) instead of materializing a host broadcast view and
    shipping the full run over PCIe; everything else (flat arrays,
    dictionary codes, lazy columns on first touch) passes through as
    ``.data``."""
    if col.encoding == "RLE":
        return K.rle_fill(col.rle_value, len(col))
    return col.data


class DeviceJoinTable:
    """Sorted-hash build side, all arrays device-resident.

    The planner-visible scalars (has_null_key, live_rows, max duplicate run)
    stay on device until first access: building the table costs ZERO blocking
    host syncs, and the one combined scalar fetch happens lazily — per build,
    never per probe batch (each blocking RPC over a tunneled device costs
    ~120 ms, so per-batch scalar syncs dominated the r4 join profile)."""

    __slots__ = ("sorted_hash", "perm", "key_datas",
                 "num_rows", "_scalars", "_fetched", "dense", "dense_lo",
                 "hash_idx")

    def __init__(self, sorted_hash, perm, key_datas,
                 num_rows: int, scalars):
        self.sorted_hash = sorted_hash
        self.perm = perm
        self.key_datas = key_datas  # unsorted, for exact verify
        self.num_rows = num_rows  # physical slots (incl. dead padding)
        # (has_null, live_rows, max_run[, kmin, kmax]) device scalars OR a
        # host tuple
        self._scalars = scalars
        self._fetched: Optional[tuple] = None
        # direct-address table for a unique single-int-key build whose key
        # range is dense: dense[key - dense_lo] = build row (or -1).  Probes
        # become ONE gather — no hashing, no binary search, no verify.
        self.dense = None
        self.dense_lo = 0
        # open-addressing index over the build hashes (TRINO_TPU_HASH_IMPL):
        # probe_ranges dispatches on it; every downstream program is shared
        self.hash_idx: Optional["JoinHashTable"] = None

    def _fetch(self) -> tuple:
        if self._fetched is None:
            s = self._scalars
            if isinstance(s, tuple) and all(
                    isinstance(x, (bool, int)) for x in s):
                self._fetched = s
            else:
                # ONE blocking fetch per BUILD (never per probe batch); the
                # async copy started at build time usually landed already
                self._fetched = tuple(
                    int(x) for x in SG.fetch(s, "join.build-scalars"))
        return self._fetched

    @property
    def has_null_key(self) -> bool:  # among LIVE rows
        return bool(self._fetch()[0])

    @property
    def live_rows(self) -> int:
        return self._fetch()[1]

    @property
    def unique(self) -> bool:
        """True when every live build HASH is distinct (implies the keys are
        distinct): each probe row matches at most one build row, so the
        probe runs the static-shape path with no candidate-count sync."""
        return self._fetch()[2] <= 1

    @property
    def max_run(self) -> int:
        """Longest duplicate-hash run among live build rows: each probe row
        yields at most this many candidates, so n_probe * max_run bounds the
        pair total — the provable padded-expand cap (ExpandPlanner)."""
        return self._fetch()[2]


class JoinHashTable:
    """Open-addressing index over the build side's 64-bit key hashes
    (TRINO_TPU_HASH_IMPL, ops/pallas_kernels.py): maps a probe hash to the
    contiguous run of matching rows in sorted-hash order, replacing the two
    binary searches of probe_ranges with one kernel probe plus two gathers.
    The (lo, counts) it yields are value-identical to the searchsorted
    implementation — both index the SAME sorted order — so every downstream
    expand/verify/gather program is shared between implementations, and
    ``build_id = perm[lo + within]`` holds unchanged."""

    __slots__ = ("table_planes", "slot_gid", "group_lo", "group_counts",
                 "num_slots")

    def __init__(self, table_planes, slot_gid, group_lo, group_counts,
                 num_slots: int):
        self.table_planes = table_planes
        self.slot_gid = slot_gid
        self.group_lo = group_lo  # [S] first sorted position per hash group
        self.group_counts = group_counts  # [S] live run length per group
        self.num_slots = num_slots


def _hash_join_enabled(n_rows: int) -> bool:
    if n_rows == 0 or K.hash_impl() == "sort":
        return False
    from ..ops.pallas_kernels import pallas_available

    if not pallas_available():
        return False
    if K.hash_impl() == "pallas":
        return True
    if K._HASH_IMPL_STATE["failed"] or jax.default_backend() != "tpu":
        return False
    # 2 hash planes + slot gids + slack must stay VMEM-honest when compiled
    return 4 * K.bucket(2 * n_rows) * 4 <= K._HASH_VMEM_BUDGET


def _hash_planes(h):
    """uint64 hash -> the kernels' [2, N] uint32 planes + uint32 slot hash.
    Plane equality is exactly 64-bit hash equality, so the index reproduces
    the searchsorted candidate set bit for bit."""
    planes = jnp.stack([
        (h & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32),
        (h >> jnp.uint64(32)).astype(jnp.uint32)])
    h32 = (h ^ (h >> jnp.uint64(32))).astype(jnp.uint32)
    return planes, h32


@jit_memo("join._hash_index_fn")
def _hash_index_fn(S: int, n: int, interpret: bool):
    from ..ops import pallas_kernels as PK

    @jax.jit
    def fn(sorted_hash):
        live = sorted_hash < jnp.uint64(_SENT_PROBE)
        planes, h32 = _hash_planes(sorted_hash)
        row_gid, _count, table, sgid = PK.hash_insert(
            planes, h32, live, S, interpret=interpret)
        # the insert ran over the SORTED hashes: each distinct hash is one
        # contiguous run, so per-group lo/count are one min- and one
        # sum-scatter over positions (dead rows carry gid S -> trash slot)
        pos = jnp.arange(n, dtype=jnp.int64)
        glo = jnp.full((S + 1,), n, jnp.int64).at[row_gid].min(pos)
        gcnt = jnp.zeros((S + 1,), jnp.int64).at[row_gid].add(
            live.astype(jnp.int64))
        return table, sgid, glo[:S], gcnt[:S]

    return fn


@jit_memo("join._build_fn")
def _build_fn(num_keys: int, has_valid: tuple, has_live: bool,
              want_range: bool = False):
    @jax.jit
    def fn(*flat):
        i = 0
        datas, valids = [], []
        for k in range(num_keys):
            datas.append(flat[i])
            i += 1
            if has_valid[k]:
                valids.append(flat[i])
                i += 1
            else:
                valids.append(None)
        live = flat[i] if has_live else None
        h = K.hash_combine(datas)
        null_mask = None
        for v in valids:
            if v is not None:
                nm = ~v
                null_mask = nm if null_mask is None else (null_mask | nm)
        n = datas[0].shape[0]
        live_rows = (jnp.asarray(n, jnp.int64) if live is None
                     else jnp.sum(live))
        if null_mask is not None:
            has_null = jnp.any(null_mask if live is None
                               else (null_mask & live))
            h = jnp.where(null_mask, jnp.uint64(_SENT_BUILD), h)
        else:
            has_null = jnp.asarray(False)
        if live is not None:
            h = jnp.where(live, h, jnp.uint64(_SENT_BUILD))
        perm = jnp.argsort(h)
        sh = h[perm]
        # max duplicate-hash run among live (non-sentinel) rows: 1 means the
        # build keys are provably unique -> probes take the sync-free path
        if n:
            run = (K.searchsorted(sh, sh, side="right")
                   - K.searchsorted(sh, sh, side="left"))
            in_region = sh < jnp.uint64(_SENT_PROBE)
            max_run = jnp.max(jnp.where(in_region, run, 0))
        else:
            max_run = jnp.zeros((), jnp.int64)
        if not want_range:
            return sh, perm, has_null, live_rows, max_run
        # live non-null key min/max, for the dense direct-address table
        big = jnp.asarray(1 << 62, jnp.int64)
        if n:
            k0 = datas[0].astype(jnp.int64)
            elig = jnp.ones(k0.shape, jnp.bool_)
            if valids[0] is not None:
                elig = elig & valids[0]
            if live is not None:
                elig = elig & live
            kmin = jnp.min(jnp.where(elig, k0, big))
            kmax = jnp.max(jnp.where(elig, k0, -big))
        else:
            kmin, kmax = big, -big
        return sh, perm, has_null, live_rows, max_run, kmin, kmax

    return fn


@jit_memo("join._dense_build_fn")
def _dense_build_fn(size: int, has_valid: bool, has_live: bool, lo: int):
    """Scatter live build rows into dense[key - lo] (one scatter; -1 =
    empty slot).  Exactness needs no verify: direct addressing cannot
    collide, and uniqueness was already proven by max_run == 1."""

    @jax.jit
    def fn(key, *rest):
        i = 0
        valid = rest[i] if has_valid else None
        i += 1 if has_valid else 0
        live = rest[i] if has_live else None
        n = key.shape[0]
        idx = key.astype(jnp.int64) - lo
        elig = (idx >= 0) & (idx < size)
        if valid is not None:
            elig = elig & valid
        if live is not None:
            elig = elig & live
        slot = jnp.where(elig, idx, size)  # trash slot for ineligible rows
        dense = jnp.full((size + 1,), -1, jnp.int32)
        dense = dense.at[slot].set(jnp.arange(n, dtype=jnp.int32))
        return dense[:size]

    return fn


DENSE_MAX_SLOTS = 1 << 27  # 128M * 4B = 512MB hard cap
DENSE_SLACK = 4  # range may exceed live rows by this factor


def maybe_build_dense(table: DeviceJoinTable, keys, live) -> None:
    """Attach a direct-address table when the single int-like build key is
    unique and densely ranged (every TPC-H PK/FK edge qualifies).  Costs the
    build's ONE combined scalar fetch (which LEFT/semi probes and dynamic
    filters want anyway) plus one scatter program."""
    if len(keys) != 1 or table.num_rows == 0:
        return
    d, v = keys[0]
    kind = np.dtype(jnp.asarray(d).dtype).kind
    if kind not in "iu":
        return
    f = table._fetch()
    if len(f) < 5:
        return
    _, live_rows, max_run, kmin, kmax = f[:5]
    if max_run != 1 or kmax < kmin:
        return
    size = kmax - kmin + 1
    if size > DENSE_MAX_SLOTS or size > max(DENSE_SLACK * live_rows, 1 << 16):
        return
    flat = [jnp.asarray(d)]
    if v is not None:
        flat.append(jnp.asarray(v))
    if live is not None:
        flat.append(jnp.asarray(live))
    table.dense = _dense_build_fn(
        int(size), v is not None, live is not None, int(kmin))(*flat)
    table.dense_lo = int(kmin)


def build_table(keys: Sequence[tuple], live=None,
                num_rows: Optional[int] = None) -> DeviceJoinTable:
    """keys: [(data, valid|None), ...]; ``live`` masks dead (padded) build
    rows — they never match and don't count toward live_rows/has_null."""
    if not keys:  # cross join: every probe row pairs with every live row
        n = int(num_rows or 0)
        if live is not None:
            # live count stays a device scalar: fetched lazily, per BUILD,
            # via the table's one combined scalar sync — never per batch
            lr = jnp.sum(jnp.asarray(live))
            try:
                lr.copy_to_host_async()
            # tpulint: disable=error-taxonomy -- async-copy is a hint; backends without it keep the lazy fetch
            except Exception:
                pass
            return DeviceJoinTable(None, None, [], n, (False, lr, n))
        return DeviceJoinTable(None, None, [], n, (False, n, n))
    has_valid = tuple(v is not None for _, v in keys)
    flat: list = []
    datas = []
    for (d, v), hv in zip(keys, has_valid):
        d = jnp.asarray(d)
        datas.append(d)
        flat.append(d)
        if hv:
            flat.append(jnp.asarray(v))
    if live is not None:
        flat.append(jnp.asarray(live))
    want_range = (len(keys) == 1
                  and np.dtype(datas[0].dtype).kind in "iu")
    outs = _build_fn(len(keys), has_valid, live is not None,
                     want_range)(*flat)
    sh, perm = outs[0], outs[1]
    scalars = outs[2:]
    for s in scalars:  # start the D2H transfer; the sync happens lazily
        try:
            s.copy_to_host_async()
        # tpulint: disable=error-taxonomy -- async-copy is a hint; backends without it keep the lazy fetch
        except Exception:
            pass
    table = DeviceJoinTable(sh, perm, datas, int(datas[0].shape[0]), scalars)
    n = table.num_rows
    if _hash_join_enabled(n):
        # open-addressing index over the sorted hashes: pure device
        # programs, zero extra syncs.  Forced 'pallas' propagates failures
        # (equivalence tests must not silently run the sort path); 'auto'
        # falls back to searchsorted permanently.
        S = K.bucket(2 * n)
        try:
            table.hash_idx = JoinHashTable(
                *_hash_index_fn(S, n, K.hash_interpret())(sh), S)
        except Exception:  # noqa: BLE001
            if K.hash_impl() == "pallas":
                raise
            K._HASH_IMPL_STATE["failed"] = True
    if want_range:
        maybe_build_dense(table, keys, live)
    return table


def _probe_hash(num_keys: int, has_valid: tuple, has_remap: tuple,
                has_live: bool, flat):
    """Traced: probe-side key hash with NULL/dictionary-miss rows folded to
    the probe sentinel — the normalization shared by the searchsorted and
    the open-addressing range implementations.  Returns (h, live)."""
    i = 0
    datas, valids = [], []
    for k in range(num_keys):
        d = flat[i]
        i += 1
        if has_remap[k]:
            d = flat[i][d]  # dictionary remap table gather
            i += 1
        datas.append(d)
        if has_valid[k]:
            valids.append(flat[i])
            i += 1
        else:
            valids.append(None)
    live = flat[i] if has_live else None
    h = K.hash_combine(datas)
    pnull = None
    for k, v in enumerate(valids):
        nm = ~v if v is not None else None
        if has_remap[k]:
            # remapped code -1 = value absent from the build dictionary:
            # cannot match (but is NOT a null probe for null-aware marks)
            miss = datas[k] < 0
            nm = miss if nm is None else (nm | miss)
        if nm is not None:
            pnull = nm if pnull is None else (pnull | nm)
    if pnull is not None:
        h = jnp.where(pnull, jnp.uint64(_SENT_PROBE), h)
    return h, live


@jit_memo("join._ranges_fn")
def _ranges_fn(num_keys: int, has_valid: tuple, has_live: bool,
               has_remap: tuple):
    @jax.jit
    def fn(sorted_hash, *flat):
        h, live = _probe_hash(num_keys, has_valid, has_remap, has_live, flat)
        lo = K.searchsorted(sorted_hash, h, side="left")
        hi = K.searchsorted(sorted_hash, h, side="right")
        counts = hi - lo
        if live is not None:
            counts = jnp.where(live, counts, 0)
        # the build sentinel region (null/dead rows) must never match, and
        # null/dictionary-miss probes (folded to the probe sentinel by
        # _probe_hash) must not hit it
        counts = jnp.where(h >= jnp.uint64(_SENT_PROBE), 0, counts)
        return lo, counts, jnp.sum(counts)

    return fn


@jit_memo("join._hash_ranges_fn")
def _hash_ranges_fn(num_keys: int, has_valid: tuple, has_live: bool,
                    has_remap: tuple, S: int, interpret: bool):
    from ..ops import pallas_kernels as PK

    @jax.jit
    def fn(table_planes, slot_gid, group_lo, group_counts, *flat):
        h, live = _probe_hash(num_keys, has_valid, has_remap, has_live, flat)
        ok = h < jnp.uint64(_SENT_PROBE)
        if live is not None:
            ok = ok & live
        planes, h32 = _hash_planes(h)
        pgid = PK.hash_probe(table_planes, slot_gid, planes, h32, ok,
                             interpret=interpret)
        hit = pgid >= 0  # dead/null/miss probe rows come back -1
        safe = jnp.where(hit, pgid, 0)
        lo = group_lo[safe]
        counts = jnp.where(hit, group_counts[safe],
                           jnp.zeros((), group_counts.dtype))
        return lo, counts, jnp.sum(counts)

    return fn


def probe_ranges_device(table: DeviceJoinTable, probe_keys: Sequence[tuple],
                        remaps: Sequence[Optional[np.ndarray]], live=None):
    """probe_keys: [(data, valid|None), ...]; ``remaps[k]`` an optional
    host int32 table translating probe dictionary codes into the build code
    space (-1 = value absent).  Returns (lo, counts, total) with ALL THREE
    on device — ZERO host syncs; ``total`` comes back as a SyncGuard
    AsyncScalar whose D2H copy is already in flight."""
    has_valid = tuple(v is not None for _, v in probe_keys)
    has_remap = tuple(r is not None for r in remaps)
    flat: list = []
    for (d, v), r in zip(probe_keys, remaps):
        flat.append(jnp.asarray(d))
        if r is not None:
            flat.append(jnp.asarray(r))
        if v is not None:
            flat.append(jnp.asarray(v))
    if live is not None:
        flat.append(jnp.asarray(live))
    idx = table.hash_idx
    if idx is not None:
        lo, counts, total = _hash_ranges_fn(
            len(probe_keys), has_valid, live is not None, has_remap,
            idx.num_slots, K.hash_interpret())(
            idx.table_planes, idx.slot_gid, idx.group_lo,
            idx.group_counts, *flat)
    else:
        lo, counts, total = _ranges_fn(
            len(probe_keys), has_valid, live is not None, has_remap)(
            table.sorted_hash, *flat)
    return lo, counts, SG.async_scalar(total, "join.pair-total")


def probe_ranges(table: DeviceJoinTable, probe_keys: Sequence[tuple],
                 remaps: Sequence[Optional[np.ndarray]], live=None):
    """Legacy wrapper around :func:`probe_ranges_device` that syncs the
    candidate total to a host int — ONE blocking host sync per call."""
    lo, counts, total = probe_ranges_device(table, probe_keys, remaps, live)
    return lo, counts, int(total.get())


# ---------------------------------------------------------------------------
# padded-expand capacity planning

# the provable cap (n_probe * max_run lanes can NEVER overflow, because each
# probe row yields at most max_run candidates) is used whenever it costs at
# most this many times the minimal bucket; beyond that the adaptive estimate
# takes over and the overflow flag guards correctness
PROVABLE_SLACK = 8
EST_HEADROOM = 2          # estimated cap = headroom * max recent total
EST_WINDOW = 8            # totals remembered for the estimate


# Cross-execution feedback: the max observed candidate total per stable
# operator identity.  A fresh operator for the same plan shape seeds its
# estimate from the last execution instead of cold-starting at n_probe —
# a repartitioned probe arriving as one large page otherwise overflows its
# first cap and re-runs the whole pair program (correct, but double work).
# Correctness never depends on a seed: the overflow flag still guards
# every estimated cap, a stale seed only costs padding.
_EST_SEEDS: dict = {}
_EST_SEEDS_CAP = 4096
_EST_SEEDS_LOCK = threading.Lock()


def reset_estimate_seeds_for_test() -> None:
    with _EST_SEEDS_LOCK:
        _EST_SEEDS.clear()


class ExpandPlanner:
    """Per-probe-operator planner for the padded-expand output bucket.

    Sync-free contract: ``plan`` never touches the device.  It prefers a cap
    PROVABLY >= the candidate total (from the build's max duplicate-hash
    run — one scalar fetch per build, amortized over every batch), falling
    back to an adaptive estimate fed by asynchronously-landed totals of
    previous batches.  On the estimated path the caller must check the
    expand program's overflow flag before emitting; ``observe`` feeds the
    planner so steady state converges to zero overflows.  With a ``key``
    the planner also reads/writes the process-global seed store, so the
    convergence carries across executions of the same plan shape."""

    __slots__ = ("_totals", "_pending", "_key")

    def __init__(self, key=None):
        self._key = key
        seed = None
        if key is not None:
            with _EST_SEEDS_LOCK:
                seed = _EST_SEEDS.get(key)
        self._totals: list[int] = [seed] if seed else []
        self._pending: list[SG.AsyncScalar] = []

    def plan(self, n_probe: int, max_run: Optional[int]) -> tuple[int, bool]:
        """Returns (cap, provable).  ``max_run`` None = unknown (cross joins
        or builds whose scalars were never fetched)."""
        self._drain()
        floor = K.bucket(max(n_probe, 1))
        bound = None  # provable candidate-total upper bound
        if max_run is not None and max_run >= 0:
            bound = max(n_probe * max(max_run, 1), 1)
            if K.bucket(bound) <= PROVABLE_SLACK * floor:
                return K.bucket(bound), True
        est = max(self._totals) * EST_HEADROOM if self._totals else n_probe
        cap = K.bucket(max(est, n_probe, 1))
        if bound is not None and cap >= K.bucket(bound):
            return K.bucket(bound), True  # estimate crossed the bound
        return cap, False

    def observe_async(self, total: SG.AsyncScalar) -> None:
        """Feed a batch's device total; it is read only once its async copy
        landed (non-blocking polls on later ``plan`` calls)."""
        self._pending.append(total)

    def recent_max(self) -> Optional[int]:
        """Largest asynchronously-landed total of the recent window (None
        until the first one lands) — the unique-path density estimate."""
        self._drain()
        return max(self._totals) if self._totals else None

    def observe(self, total: int) -> None:
        total = int(total)
        self._totals.append(total)
        del self._totals[:-EST_WINDOW]
        if self._key is not None:
            with _EST_SEEDS_LOCK:
                if total > _EST_SEEDS.get(self._key, 0):
                    if (self._key not in _EST_SEEDS
                            and len(_EST_SEEDS) >= _EST_SEEDS_CAP):
                        _EST_SEEDS.clear()  # coarse bound; seeds re-learn
                    _EST_SEEDS[self._key] = total

    def _drain(self) -> None:
        still = []
        for h in self._pending:
            v = h.get_if_ready()
            if v is None:
                still.append(h)
            else:
                self.observe(int(v))
        self._pending = still[-EST_WINDOW:]


MAX_INFLIGHT = 4  # deferred estimated-cap batches before the host backs off


class OverflowQueue:
    """Deferred commits for estimated-cap expand programs.

    An estimated cap can truncate candidates, and the only proof it didn't
    is the program's device overflow flag — but blocking on that flag per
    batch would reintroduce exactly the sync the padded expand removed.  So
    the speculative result parks here with the flag's async copy in flight;
    the flag of batch N lands while the host dispatches batch N+1, and
    ``drain`` commits it with a non-blocking poll.  The rare landed-True
    entry re-runs via its ``retry`` thunk at the exact (by then host-known)
    total before committing — results are never silently truncated, and the
    retry is counted in SyncStats (``expand_overflows``/``expand_retries``).

    Entries commit in push order; only ``drain(block=True)`` (input end /
    more than MAX_INFLIGHT parked) ever blocks."""

    __slots__ = ("_q",)

    def __init__(self):
        from collections import deque

        self._q = deque()

    def __len__(self) -> int:
        return len(self._q)

    def push(self, overflow: SG.AsyncScalar, result, retry, commit) -> None:
        self._q.append((overflow, result, retry, commit))

    def drain(self, block: bool = False) -> None:
        while self._q:
            h, res, retry, commit = self._q[0]
            if block or len(self._q) > MAX_INFLIGHT:
                v = h.get()
            else:
                v = h.get_if_ready()
                if v is None:
                    return
            self._q.popleft()
            if bool(v):
                SG.count_overflow()
                res = retry()
            commit(res)


# ---------------------------------------------------------------------------
# pair expansion + verify + residual + output gather: one program

_PAIR_CACHE: dict = {}
_PAIR_LOCK = threading.Lock()
_PAIR_CACHE_MAX = 1024

# dictionary identity tokens: monotonically assigned, NEVER recycled while
# the dictionary object is alive (checked via weakref), so a cache key built
# from tokens cannot alias a new dictionary at a recycled id() — which made
# eviction unsafe in the r4 id()-keyed design (advisor r4 medium).  With
# stable tokens the LRU eviction below is safe and nothing needs pinning.
_DICT_TOKENS: dict[int, tuple] = {}  # id(d) -> (weakref|strong-thunk, token)
_DICT_SEQ = 0


def _dict_token(d):
    global _DICT_SEQ
    if d is None:
        return None
    import weakref

    i = id(d)
    ent = _DICT_TOKENS.get(i)
    if ent is not None and ent[0]() is d:
        return ent[1]
    _DICT_SEQ += 1
    tok = _DICT_SEQ
    try:
        # the collection callback fires before the id can be reused, so it
        # cannot delete a newer entry — keeps the table bounded by LIVE dicts
        ref = weakref.ref(d, lambda _r, _i=i: _DICT_TOKENS.pop(_i, None))
    except TypeError:  # not weakrefable: keep it alive so the id can't recycle
        ref = (lambda _d=d: _d)
    _DICT_TOKENS[i] = (ref, tok)
    return tok


def _donate_ok() -> bool:
    """Buffer donation saves HBM on real accelerators; the CPU backend warns
    about unusable donations, so only donate off-CPU."""
    try:
        return jax.default_backend() != "cpu"
    except Exception:
        return False


def _make_pair_fn(cap: int, num_keys: int, has_pvalid: tuple,
                  has_remap: tuple, pair_types, pair_dicts,
                  n_probe_cols: int, n_build_cols: int,
                  pcol_has_valid: tuple, bcol_has_valid: tuple,
                  residual: Optional[RowExpression],
                  need_matched: bool, semi: Optional[tuple],
                  donate: bool = False):
    """Build the pair program.  Flat operand order:
    lo, counts, total, perm,
    per probe key: data [remap] [valid],
    per probe col: data [valid],
    per build col: data [valid],
    build key datas.

    Besides the pair outputs the program emits ``overflow`` — a device bool
    flagging ``total > cap`` (candidates truncated; caller must re-run at a
    larger bucket).  ``donate`` releases the lo/counts operand buffers into
    the program (only safe when the caller provably never retries).

    ``semi``: None for a regular join; (null_aware, has_null_build,
    build_nonempty) for the semi-join mark variant (outputs (mark, valid)
    instead of gathered pair columns)."""
    res_fn = (compile_expression(residual, list(pair_types), list(pair_dicts))
              if residual is not None else None)

    def fn(lo, counts, total, perm, *flat):
        i = 0
        pkeys, pkvalids = [], []
        for k in range(num_keys):
            d = flat[i]
            i += 1
            if has_remap[k]:
                d = flat[i][d]
                i += 1
            pkeys.append(d)
            if has_pvalid[k]:
                pkvalids.append(flat[i])
                i += 1
            else:
                pkvalids.append(None)
        pcols = []
        for c in range(n_probe_cols):
            d = flat[i]
            i += 1
            v = None
            if pcol_has_valid[c]:
                v = flat[i]
                i += 1
            pcols.append((d, v))
        bcols = []
        for c in range(n_build_cols):
            d = flat[i]
            i += 1
            v = None
            if bcol_has_valid[c]:
                v = flat[i]
                i += 1
            bcols.append((d, v))
        bkeys = list(flat[i:i + num_keys])

        n_probe = pkeys[0].shape[0] if pkeys else (
            pcols[0][0].shape[0] if pcols else 1)
        nb = perm.shape[0]
        ends = jnp.cumsum(counts)
        starts = ends - counts
        slot = jnp.arange(cap)
        probe_id = jnp.clip(
            K.searchsorted(ends, slot, side="right"), 0, n_probe - 1)
        within = slot - starts[probe_id]
        build_pos = lo[probe_id] + within
        build_id = perm[jnp.clip(build_pos, 0, nb - 1)]
        ok = slot < total
        for pk, bk in zip(pkeys, bkeys):
            ok = ok & ~K._neq(pk[probe_id], bk[build_id])

        pairs = None
        if semi is None or res_fn is not None:
            pairs = [(d[probe_id], None if v is None else v[probe_id])
                     for d, v in pcols]
            pairs += [(d[build_id], None if v is None else v[build_id])
                      for d, v in bcols]
        if res_fn is not None:
            rd, rv = res_fn(pairs)
            rmask = rd if rv is None else (rd & rv)
            if getattr(rmask, "ndim", 1) == 0:
                rmask = jnp.broadcast_to(rmask, (cap,))
            ok = ok & rmask

        matched = None
        max_per_probe = None
        if need_matched or semi is not None:
            # per-probe match count: pairs are sorted by probe_id, so the
            # count is a prefix-sum difference at segment boundaries
            # (scatters serialize on TPU; this is all gathers)
            cs = jnp.cumsum(ok.astype(jnp.int64))
            pr = jnp.arange(n_probe)
            pend = K.searchsorted(probe_id, pr, side="right")
            pstart = K.searchsorted(probe_id, pr, side="left")
            hi2 = cs[jnp.maximum(pend - 1, 0)]
            lo2 = jnp.where(pstart > 0, cs[jnp.maximum(pstart - 1, 0)],
                            jnp.zeros((), jnp.int64))
            cnt = jnp.where(pend > pstart, hi2 - lo2, 0)
            matched = cnt > 0
            max_per_probe = jnp.max(cnt)

        overflow = jnp.asarray(total, jnp.int64) > cap
        if semi is not None:
            # three-valued NOT IN: a non-match is UNKNOWN (NULL mark) when
            # the probe key is NULL or the build side contains a NULL key;
            # IN over the empty set is FALSE even for NULL probes
            null_aware, has_null_build, build_nonempty = semi
            mark_valid = None
            if null_aware and build_nonempty:
                if has_null_build:
                    unknown = ~matched
                else:
                    null_probe = jnp.zeros((n_probe,), jnp.bool_)
                    for v in pkvalids:
                        if v is not None:
                            null_probe = null_probe | ~v
                    unknown = ~matched & null_probe
                mark_valid = ~unknown
            return (None, ok, matched, max_per_probe, (matched, mark_valid),
                    overflow)
        return pairs, ok, matched, max_per_probe, build_id, overflow

    if donate:
        return jax.jit(fn, donate_argnums=(0, 1))  # lo, counts
    return jax.jit(fn)


def run_pairs(table: DeviceJoinTable, lo, counts, total,
              probe_keys, remaps, probe_cols, build_cols,
              pair_types, pair_dicts,
              residual: Optional[RowExpression],
              need_matched: bool, semi: Optional[tuple] = None,
              cap: Optional[int] = None, donate: bool = False):
    """Execute the pair program.  Returns (pair_cols|None, pair_live,
    matched|None, max_per_probe|None, mark|None, overflow) — ALL device
    arrays, zero host syncs.  ``pair_cols`` is [(data, valid|None), ...]
    over probe cols then build cols, gathered at the matched pairs.  The
    5th element is the device build_id per pair slot for a regular join, or
    the (data, valid) semi-join mark when ``semi`` is set.

    ``total`` may be a host int (legacy, picks ``cap`` exactly) or a device
    scalar (sync-free; ``cap`` must then be given, chosen from build-side
    statistics — see :class:`ExpandPlanner`).  ``overflow`` is a device bool:
    True means the ``cap`` bucket truncated candidates and the batch must be
    re-run at a larger cap (results are otherwise a silent subset).
    ``donate`` releases lo/counts into the program — only when no retry can
    follow (the provable-cap path)."""
    if cap is None:
        cap = K.bucket(max(int(total), 1))
    donate = donate and _donate_ok()
    has_pvalid = tuple(v is not None for _, v in probe_keys)
    has_remap = tuple(r is not None for r in remaps)
    pcol_has_valid = tuple(v is not None for _, v in probe_cols)
    bcol_has_valid = tuple(v is not None for _, v in build_cols)
    with _PAIR_LOCK:
        key = (cap, len(probe_keys), has_pvalid, has_remap,
               tuple(str(t) for t in pair_types),
               tuple(_dict_token(d) for d in pair_dicts),
               len(probe_cols), len(build_cols), pcol_has_valid,
               bcol_has_valid, residual, need_matched, semi, donate)
        prog = _PAIR_CACHE.pop(key, None)
        if prog is not None:  # re-insert: dict ordering = LRU order
            _PAIR_CACHE[key] = prog
    if prog is None:
        prog = _make_pair_fn(cap, len(probe_keys), has_pvalid, has_remap,
                             list(pair_types), list(pair_dicts),
                             len(probe_cols), len(build_cols),
                             pcol_has_valid, bcol_has_valid,
                             residual, need_matched, semi, donate)
        with _PAIR_LOCK:
            prog = _PAIR_CACHE.setdefault(key, prog)
            while len(_PAIR_CACHE) > _PAIR_CACHE_MAX:
                _PAIR_CACHE.pop(next(iter(_PAIR_CACHE)))

    flat: list = []
    for (d, v), r in zip(probe_keys, remaps):
        flat.append(jnp.asarray(d))
        if r is not None:
            flat.append(jnp.asarray(r))
        if v is not None:
            flat.append(jnp.asarray(v))
    for d, v in probe_cols:
        flat.append(jnp.asarray(d))
        if v is not None:
            flat.append(jnp.asarray(v))
    for d, v in build_cols:
        flat.append(jnp.asarray(d))
        if v is not None:
            flat.append(jnp.asarray(v))
    flat.extend(table.key_datas)
    total_dev = (total.value if isinstance(total, SG.AsyncScalar)
                 else jnp.asarray(total, jnp.int64))
    pairs, ok, matched, maxc, extra, overflow = prog(
        lo, counts, total_dev, table.perm, *flat)
    return pairs, ok, matched, maxc, extra, overflow


# ---------------------------------------------------------------------------
# unique-build INNER/RIGHT probe: ranges + count, then a width-adaptive gather
#
# Profile-driven split (r5): gathering every output column at the probe
# batch's full static width costs O(probe_lanes) random reads per column —
# for a selective join that is the dominant device cost.  So the probe runs
# as TWO programs around ONE combined scalar sync:
#   A (`run_unique_ranges`)  — hash + binary search + exact verify; returns
#       (match mask, build row per lane, match count, build max-run) with
#       the count/max-run fetched together in a single RTT.  The max-run
#       rides along so the build table needs NO separate scalar fetch: a
#       duplicate-key build (max_run > 1) falls back to the pair path.
#   B (`run_unique_gather`)  — if matches are sparse, compact (probe cols +
#       build ids) to bucket(count) lanes FIRST and gather build columns at
#       O(count); if dense, gather wide.  Residual and the RIGHT-join
#       matched-build scatter evaluate on the narrow lanes.


@jit_memo("join._uranges_fn")
def _uranges_fn(num_keys: int, has_pvalid: tuple, has_remap: tuple,
                has_live: bool):
    @jax.jit
    def fn(sorted_hash, perm, max_run, *flat):
        i = 0
        pkeys, pkvalids = [], []
        for k in range(num_keys):
            d = flat[i]
            i += 1
            if has_remap[k]:
                d = flat[i][d]
                i += 1
            pkeys.append(d)
            if has_pvalid[k]:
                pkvalids.append(flat[i])
                i += 1
            else:
                pkvalids.append(None)
        bkeys = list(flat[i:i + num_keys])
        i += num_keys
        live = flat[i] if has_live else None

        h = K.hash_combine(pkeys)
        pnull = None
        for k, v in enumerate(pkvalids):
            nm = ~v if v is not None else None
            if has_remap[k]:
                miss = pkeys[k] < 0
                nm = miss if nm is None else (nm | miss)
            if nm is not None:
                pnull = nm if pnull is None else (pnull | nm)
        if pnull is not None:
            h = jnp.where(pnull, jnp.uint64(_SENT_PROBE), h)
        nb = perm.shape[0]
        lo = jnp.clip(K.searchsorted(sorted_hash, h, side="left"), 0, nb - 1)
        found = (sorted_hash[lo] == h) & (h < jnp.uint64(_SENT_PROBE))
        bid = perm[lo]
        ok = found
        for pk, bk in zip(pkeys, bkeys):
            ok = ok & ~K._neq(pk, bk[bid])
        if live is not None:
            ok = ok & live
        return ok, bid, jnp.sum(ok), max_run

    return fn


@jit_memo("join._dense_uranges_fn")
def _dense_uranges_fn(size: int, lo: int, has_pvalid: bool, has_remap: bool,
                      has_live: bool):
    """Program A over a direct-address build: ONE gather per probe row —
    no hashing, no binary search, no verify (direct addressing is exact)."""

    @jax.jit
    def fn(dense, *flat):
        i = 0
        d = flat[i]
        i += 1
        if has_remap:
            d = flat[i][d]
            i += 1
        valid = flat[i] if has_pvalid else None
        i += 1 if has_pvalid else 0
        live = flat[i] if has_live else None
        idx = d.astype(jnp.int64) - lo
        in_range = (idx >= 0) & (idx < size)
        if has_remap:
            in_range = in_range & (d >= 0)
        bid = dense[jnp.clip(idx, 0, size - 1)]
        ok = in_range & (bid >= 0)
        if valid is not None:
            ok = ok & valid
        if live is not None:
            ok = ok & live
        return ok, bid.astype(jnp.int64), jnp.sum(ok)

    return fn


def run_unique_ranges_device(table: DeviceJoinTable, probe_keys, remaps,
                             live=None):
    """Program A, sync-free: returns (ok_live, bid, count) with the count a
    SyncGuard AsyncScalar (D2H copy in flight, never blocked on).  The
    caller must already know the build is unique (``table.unique`` — one
    scalar fetch per BUILD); probing a duplicate-key build through this
    entry point silently drops matches."""
    has_pvalid = tuple(v is not None for _, v in probe_keys)
    has_remap = tuple(r is not None for r in remaps)
    if table.dense is not None and len(probe_keys) == 1:
        d, v = probe_keys[0]
        flat = [jnp.asarray(d)]
        if remaps[0] is not None:
            flat.append(jnp.asarray(remaps[0]))
        if v is not None:
            flat.append(jnp.asarray(v))
        if live is not None:
            flat.append(jnp.asarray(live))
        ok, bid, cnt = _dense_uranges_fn(
            int(table.dense.shape[0]), table.dense_lo,
            has_pvalid[0], has_remap[0], live is not None)(
            table.dense, *flat)
        return ok, bid, SG.async_scalar(cnt, "join.unique-count")
    flat = []
    for (d, v), r in zip(probe_keys, remaps):
        flat.append(jnp.asarray(d))
        if r is not None:
            flat.append(jnp.asarray(r))
        if v is not None:
            flat.append(jnp.asarray(v))
    flat.extend(table.key_datas)
    if live is not None:
        flat.append(jnp.asarray(live))
    mr_in = table._scalars[2] if not isinstance(table._scalars, tuple) \
        else jnp.asarray(table._scalars[2])
    ok, bid, cnt, _mr = _uranges_fn(
        len(probe_keys), has_pvalid, has_remap, live is not None)(
        table.sorted_hash, table.perm, mr_in, *flat)
    return ok, bid, SG.async_scalar(cnt, "join.unique-count")


def run_unique_ranges(table: DeviceJoinTable, probe_keys, remaps, live=None):
    """Program A.  Returns (ok_live, bid, count:int, max_run:int) with ONE
    combined scalar sync; max_run > 1 means the build was not unique and the
    mask/ids must be discarded in favor of the pair path.  A dense build
    takes the direct-address variant (uniqueness already proven: max_run
    returns as 1 with no extra device work)."""
    has_pvalid = tuple(v is not None for _, v in probe_keys)
    has_remap = tuple(r is not None for r in remaps)
    if table.dense is not None and len(probe_keys) == 1:
        d, v = probe_keys[0]
        flat = [jnp.asarray(d)]
        if remaps[0] is not None:
            flat.append(jnp.asarray(remaps[0]))
        if v is not None:
            flat.append(jnp.asarray(v))
        if live is not None:
            flat.append(jnp.asarray(live))
        ok, bid, cnt = _dense_uranges_fn(
            int(table.dense.shape[0]), table.dense_lo,
            has_pvalid[0], has_remap[0], live is not None)(
            table.dense, *flat)
        return ok, bid, int(SG.fetch(cnt, "join.unique-count")), 1
    flat = []
    for (d, v), r in zip(probe_keys, remaps):
        flat.append(jnp.asarray(d))
        if r is not None:
            flat.append(jnp.asarray(r))
        if v is not None:
            flat.append(jnp.asarray(v))
    flat.extend(table.key_datas)
    if live is not None:
        flat.append(jnp.asarray(live))
    mr_in = table._scalars[2] if not isinstance(table._scalars, tuple) \
        else jnp.asarray(table._scalars[2])
    ok, bid, cnt, mr = _uranges_fn(
        len(probe_keys), has_pvalid, has_remap, live is not None)(
        table.sorted_hash, table.perm, mr_in, *flat)
    cnt_h, mr_h = SG.fetch((cnt, mr), "join.unique-count+run")
    return ok, bid, int(cnt_h), int(mr_h)


def _make_ugather_fn(cap: Optional[int], pair_types, pair_dicts,
                     n_probe_cols: int, n_build_cols: int,
                     pcol_has_valid: tuple, bcol_has_valid: tuple,
                     residual: Optional[RowExpression],
                     need_build_matched: bool):
    """Program B.  ``cap`` None = wide (lanes = probe width, probe columns
    pass through untouched); otherwise compact to ``cap`` lanes first."""
    res_fn = (compile_expression(residual, list(pair_types), list(pair_dicts))
              if residual is not None else None)

    def fn(ok_live, bid, *flat):
        i = 0
        pcols = []
        for c in range(n_probe_cols):
            d = flat[i]
            i += 1
            v = None
            if pcol_has_valid[c]:
                v = flat[i]
                i += 1
            pcols.append((d, v))
        bcols = []
        for c in range(n_build_cols):
            d = flat[i]
            i += 1
            v = None
            if bcol_has_valid[c]:
                v = flat[i]
                i += 1
            bcols.append((d, v))

        overflow = None
        if cap is not None:
            # truncation guard: more matches than compact lanes means the
            # batch must re-run wide (or at a bigger cap)
            overflow = jnp.sum(ok_live.astype(jnp.int64)) > cap
            order = jnp.argsort(~ok_live)[:cap]
            ok_c = ok_live[order]
            bid_c = bid[order]
            p_out = [(d[order], None if v is None else v[order])
                     for d, v in pcols]
        else:
            ok_c, bid_c = ok_live, bid
            p_out = list(pcols)
        b_out = [(d[bid_c], None if v is None else v[bid_c])
                 for d, v in bcols]
        if res_fn is not None:
            rd, rv = res_fn(p_out + b_out)
            rmask = rd if rv is None else (rd & rv)
            if getattr(rmask, "ndim", 1) == 0:
                rmask = jnp.broadcast_to(rmask, ok_c.shape)
            ok_c = ok_c & rmask
        build_matched = None
        if need_build_matched:
            nb = 0
            for d, _ in bcols:
                nb = d.shape[0]
                break
            build_matched = jnp.zeros((nb,), jnp.bool_).at[bid_c].max(ok_c)
        b_out = [(d, (ok_c if v is None else (v & ok_c)))
                 for d, v in b_out]
        return tuple(p_out), tuple(b_out), ok_c, build_matched, overflow

    return jax.jit(fn)


def plan_unique_cap(n_lanes: int, count: Optional[int]) -> Optional[int]:
    """Compact-vs-wide decision for program B: compact to bucket(count) when
    matches fill < 1/4 of the lanes, else stay wide (None).  ``count`` may be
    an exact host int (legacy) or an estimate from a previous batch's
    asynchronously-landed count (sync-free; overflow flag guards it)."""
    if count is None:
        return None
    return K.bucket(max(count, 1)) if count * 4 <= n_lanes else None


def run_unique_gather(table: DeviceJoinTable, ok_live, bid,
                      cap: Optional[int],
                      probe_cols, build_cols, pair_types, pair_dicts,
                      residual: Optional[RowExpression],
                      need_build_matched: bool):
    """Program B dispatch at a planner-chosen ``cap`` (None = wide).
    Returns (probe_out|None, build_out, live, build_matched, overflow) —
    probe_out is None on the wide path (original columns pass through);
    ``overflow`` is a device bool on the compact path (True = cap truncated
    matches, caller must re-run wide or bigger) and None on the wide path,
    which cannot overflow."""
    if cap is None and residual is None:
        # wide + residual-free: probe columns pass through OUTSIDE the
        # program (feeding them through a jit identity would copy them)
        probe_cols = []
    pcol_has_valid = tuple(v is not None for _, v in probe_cols)
    bcol_has_valid = tuple(v is not None for _, v in build_cols)
    with _PAIR_LOCK:
        key = ("ugather", cap, tuple(str(t) for t in pair_types),
               tuple(_dict_token(d) for d in pair_dicts),
               len(probe_cols), len(build_cols), pcol_has_valid,
               bcol_has_valid, residual, need_build_matched)
        prog = _PAIR_CACHE.pop(key, None)
        if prog is not None:
            _PAIR_CACHE[key] = prog
    if prog is None:
        prog = _make_ugather_fn(cap, list(pair_types), list(pair_dicts),
                                len(probe_cols), len(build_cols),
                                pcol_has_valid, bcol_has_valid,
                                residual, need_build_matched)
        with _PAIR_LOCK:
            prog = _PAIR_CACHE.setdefault(key, prog)
            while len(_PAIR_CACHE) > _PAIR_CACHE_MAX:
                _PAIR_CACHE.pop(next(iter(_PAIR_CACHE)))
    flat: list = []
    for d, v in probe_cols:
        flat.append(jnp.asarray(d))
        if v is not None:
            flat.append(jnp.asarray(v))
    for d, v in build_cols:
        flat.append(jnp.asarray(d))
        if v is not None:
            flat.append(jnp.asarray(v))
    p_out, b_out, live, bm, overflow = prog(ok_live, bid, *flat)
    return (None if cap is None else p_out), b_out, live, bm, overflow


# ---------------------------------------------------------------------------
# unique-build probe: the sync-free static-shape fast path

def _make_unique_fn(num_keys: int, has_pvalid: tuple, has_remap: tuple,
                    pair_types, pair_dicts,
                    n_probe_cols: int, n_build_cols: int,
                    pcol_has_valid: tuple, bcol_has_valid: tuple,
                    residual: Optional[RowExpression],
                    need_build_matched: bool, semi: Optional[tuple],
                    has_live: bool,
                    dense: Optional[tuple] = None):
    """Probe program for builds whose live hashes are all distinct (every
    FK->PK join): each probe row matches at most one build row, so the
    output keeps the PROBE batch's static shape — probe columns pass
    through untouched, build columns arrive as a single gather, and the
    match mask becomes the live mask.  No candidate-count sync, no
    expansion, no data-dependent shapes (reference contrast:
    operator/join/LookupJoinOperator.java:37 emits variable-length pages;
    here variable cardinality is impossible by construction).

    Flat operand order: per probe key: data [remap] [valid];
    per probe col: data [valid]; per build col: data [valid];
    build key datas; [live]."""
    res_fn = (compile_expression(residual, list(pair_types), list(pair_dicts))
              if residual is not None else None)

    def fn(sorted_hash, perm, *flat):
        i = 0
        pkeys, pkvalids = [], []
        for k in range(num_keys):
            d = flat[i]
            i += 1
            if has_remap[k]:
                d = flat[i][d]
                i += 1
            pkeys.append(d)
            if has_pvalid[k]:
                pkvalids.append(flat[i])
                i += 1
            else:
                pkvalids.append(None)
        pcols = []
        for c in range(n_probe_cols):
            d = flat[i]
            i += 1
            v = None
            if pcol_has_valid[c]:
                v = flat[i]
                i += 1
            pcols.append((d, v))
        bcols = []
        for c in range(n_build_cols):
            d = flat[i]
            i += 1
            v = None
            if bcol_has_valid[c]:
                v = flat[i]
                i += 1
            bcols.append((d, v))
        bkeys = list(flat[i:i + num_keys])
        i += num_keys
        live = flat[i] if has_live else None

        if dense is not None:
            # direct-address lookup: sorted_hash carries the dense table
            size, dlo = dense
            nb = bkeys[0].shape[0] if bkeys else 0
            idx = pkeys[0].astype(jnp.int64) - dlo
            in_range = (idx >= 0) & (idx < size)
            if has_remap[0]:
                in_range = in_range & (pkeys[0] >= 0)
            slot = sorted_hash[jnp.clip(idx, 0, size - 1)]
            ok = in_range & (slot >= 0)
            bid = jnp.clip(slot.astype(jnp.int64), 0, max(nb - 1, 0))
            if pkvalids[0] is not None:
                ok = ok & pkvalids[0]
        else:
            h = K.hash_combine(pkeys)
            pnull = None
            for k, v in enumerate(pkvalids):
                nm = ~v if v is not None else None
                if has_remap[k]:
                    miss = pkeys[k] < 0
                    nm = miss if nm is None else (nm | miss)
                if nm is not None:
                    pnull = nm if pnull is None else (pnull | nm)
            if pnull is not None:
                h = jnp.where(pnull, jnp.uint64(_SENT_PROBE), h)
            nb = perm.shape[0]
            lo = jnp.clip(K.searchsorted(sorted_hash, h, side="left"),
                          0, nb - 1)
            found = (sorted_hash[lo] == h) & (h < jnp.uint64(_SENT_PROBE))
            bid = perm[lo]
            ok = found
            for pk, bk in zip(pkeys, bkeys):
                ok = ok & ~K._neq(pk, bk[bid])

        bgather = [(d[bid], None if v is None else v[bid]) for d, v in bcols]
        if res_fn is not None:
            rd, rv = res_fn(list(pcols) + bgather)
            rmask = rd if rv is None else (rd & rv)
            if getattr(rmask, "ndim", 1) == 0:
                rmask = jnp.broadcast_to(rmask, ok.shape)
            ok = ok & rmask
        ok_live = ok if live is None else (ok & live)

        build_matched = None
        if need_build_matched:
            build_matched = jnp.zeros((nb,), jnp.bool_).at[bid].max(ok_live)

        if semi is not None:
            null_aware, has_null_build, build_nonempty = semi
            mark_valid = None
            if null_aware and build_nonempty:
                if has_null_build:
                    unknown = ~ok
                else:
                    null_probe = jnp.zeros(ok.shape, jnp.bool_)
                    for v in pkvalids:
                        if v is not None:
                            null_probe = null_probe | ~v
                    unknown = ~ok & null_probe
                mark_valid = ~unknown
            return (), ok_live, build_matched, (ok, mark_valid)

        out = tuple((d, (ok_live if v is None else (v & ok_live)))
                    for d, v in bgather)
        return out, ok_live, build_matched, None

    return jax.jit(fn)


def run_unique(table: DeviceJoinTable, probe_keys, remaps,
               probe_cols, build_cols, pair_types, pair_dicts,
               residual: Optional[RowExpression],
               need_build_matched: bool, semi: Optional[tuple] = None,
               live=None):
    """Execute the unique-build probe.  Returns (build_out, ok_live,
    build_matched|None, mark|None) — all device, ZERO host syncs.
    ``build_out`` is [(data, valid)] over build cols gathered per probe row
    (valid already folds the match mask, so unmatched rows read NULL);
    ``ok_live`` is the per-probe match mask & live."""
    has_pvalid = tuple(v is not None for _, v in probe_keys)
    has_remap = tuple(r is not None for r in remaps)
    pcol_has_valid = tuple(v is not None for _, v in probe_cols)
    bcol_has_valid = tuple(v is not None for _, v in build_cols)
    dense = None
    if table.dense is not None and len(probe_keys) == 1:
        dense = (int(table.dense.shape[0]), table.dense_lo)
    with _PAIR_LOCK:
        key = ("unique", len(probe_keys), has_pvalid, has_remap,
               tuple(str(t) for t in pair_types),
               tuple(_dict_token(d) for d in pair_dicts),
               len(probe_cols), len(build_cols), pcol_has_valid,
               bcol_has_valid, residual, need_build_matched, semi,
               live is not None, dense)
        prog = _PAIR_CACHE.pop(key, None)
        if prog is not None:
            _PAIR_CACHE[key] = prog
    if prog is None:
        prog = _make_unique_fn(len(probe_keys), has_pvalid, has_remap,
                               list(pair_types), list(pair_dicts),
                               len(probe_cols), len(build_cols),
                               pcol_has_valid, bcol_has_valid,
                               residual, need_build_matched, semi,
                               live is not None, dense)
        with _PAIR_LOCK:
            prog = _PAIR_CACHE.setdefault(key, prog)
            while len(_PAIR_CACHE) > _PAIR_CACHE_MAX:
                _PAIR_CACHE.pop(next(iter(_PAIR_CACHE)))

    flat: list = []
    for (d, v), r in zip(probe_keys, remaps):
        flat.append(jnp.asarray(d))
        if r is not None:
            flat.append(jnp.asarray(r))
        if v is not None:
            flat.append(jnp.asarray(v))
    for d, v in probe_cols:
        flat.append(jnp.asarray(d))
        if v is not None:
            flat.append(jnp.asarray(v))
    for d, v in build_cols:
        flat.append(jnp.asarray(d))
        if v is not None:
            flat.append(jnp.asarray(v))
    flat.extend(table.key_datas)
    if live is not None:
        flat.append(jnp.asarray(live))
    first = table.dense if dense is not None else table.sorted_hash
    return prog(first, table.perm, *flat)
