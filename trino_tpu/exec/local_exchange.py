"""Intra-task local exchange: bounded repartitioning between pipelines.

The LocalExchange equivalent (reference: operator/exchange/LocalExchange.
java:67, inserted by optimizations/AddLocalExchanges.java:111): N producer
drivers deposit batches, M consumer drivers drain their partition, with a
bounded per-consumer buffer providing BACKPRESSURE — a full buffer makes the
sink decline input (``needs_input() == False``), which parks the producer
driver instead of growing memory (the isBlocked() contract of
operator/Operator.java:21).

Modes (reference: PartitioningExchanger / RandomExchanger /
PassthroughExchanger):

- ``GATHER``      — all batches to consumer 0 (the many-to-one union).
- ``PASSTHROUGH`` — producer i feeds consumer i % M, whole batches.
- ``ROUND_ROBIN`` — whole batches rotate across consumers.
- ``HASH``        — rows route by key hash.  Device-resident batches are
  NOT moved: every consumer receives the same device arrays with a
  partition-restricted ``live`` mask (an on-chip "exchange" is just a mask —
  rows never leave HBM; the downstream blocking operator's live-compaction
  shrinks its partition before any O(n log n) work).  Host batches
  materialize per-partition compacted copies (numpy take).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional, Sequence

import numpy as np

from ..spi.batch import ColumnBatch
from .operators import Operator

__all__ = ["LocalExchange", "LocalExchangeSinkOperator",
           "LocalExchangeSourceOperator"]

GATHER = "GATHER"
PASSTHROUGH = "PASSTHROUGH"
ROUND_ROBIN = "ROUND_ROBIN"
HASH = "HASH"


class LocalExchange:
    def __init__(self, n_producers: int, n_consumers: int, mode: str,
                 key_channels: Sequence[int] = (),
                 buffer_batches: int = 8):
        self.n_producers = n_producers
        self.n_consumers = n_consumers
        self.mode = mode
        self.key_channels = list(key_channels)
        self.buffer_batches = buffer_batches
        self._queues: list[deque] = [deque() for _ in range(n_consumers)]
        self._lock = threading.Lock()
        self._finished_producers = 0
        self._rr = 0
        self._partition_cache: dict = {}

    # ------------------------------------------------------------- producers
    def can_accept(self, producer_index: int) -> bool:
        """False when a target buffer is full: the sink declines input and
        the producer driver parks (bounded memory in every scheduler mode)."""
        with self._lock:
            if self.mode == PASSTHROUGH:
                q = [self._queues[producer_index % self.n_consumers]]
            else:
                q = self._queues
            return all(len(x) < self.buffer_batches for x in q)

    def deposit(self, producer_index: int, batch: ColumnBatch) -> None:
        if batch.num_rows == 0:
            return
        if self.mode == GATHER:
            with self._lock:
                self._queues[0].append(batch)
            return
        if self.mode == PASSTHROUGH:
            with self._lock:
                self._queues[producer_index % self.n_consumers].append(batch)
            return
        if self.mode == ROUND_ROBIN:
            with self._lock:
                self._queues[self._rr].append(batch)
                self._rr = (self._rr + 1) % self.n_consumers
            return
        assert self.mode == HASH
        parts = self._partition(batch)
        with self._lock:
            for j, sub in enumerate(parts):
                if sub is not None and sub.num_rows:
                    self._queues[j].append(sub)

    def _partition(self, batch: ColumnBatch) -> list[Optional[ColumnBatch]]:
        """Split by key hash.  Device batches split as shared-array live-mask
        views (zero data movement on chip); host batches split as compacted
        numpy copies."""
        from . import kernels as K

        m = self.n_consumers
        keys = [(batch.columns[ch].data, batch.columns[ch].valid)
                for ch in self.key_channels]
        on_device = bool(batch.columns) and not isinstance(
            batch.columns[0].data, np.ndarray)
        if on_device:
            import jax.numpy as jnp

            h = K.hash_combine([jnp.asarray(d) for d, _ in keys])
            part = (h % jnp.uint64(m)).astype(jnp.int32)
            null_mask = None
            for _, v in keys:
                if v is not None:
                    nm = ~jnp.asarray(v)
                    null_mask = nm if null_mask is None else (null_mask | nm)
            if null_mask is not None:
                part = jnp.where(null_mask, 0, part)
            live = (jnp.asarray(batch.live) if batch.live is not None
                    else jnp.ones(batch.num_rows, jnp.bool_))
            return [
                ColumnBatch(batch.names, list(batch.columns),
                            live & (part == j))
                for j in range(m)
            ]
        part = K.partition_assignments(keys, m)
        part = np.asarray(part)
        if batch.live is not None:
            alive = np.asarray(batch.live)
        else:
            alive = None
        out: list[Optional[ColumnBatch]] = []
        for j in range(m):
            mask = part == j
            if alive is not None:
                mask = mask & alive
            idx = np.nonzero(mask)[0]
            if not len(idx):
                out.append(None)
                continue
            cols = [c.take(idx) for c in batch.columns]
            out.append(ColumnBatch(list(batch.names), cols))
        return out

    def producer_finished(self) -> None:
        with self._lock:
            self._finished_producers += 1

    # ------------------------------------------------------------- consumers
    def poll(self, consumer_index: int) -> Optional[ColumnBatch]:
        with self._lock:
            q = self._queues[consumer_index]
            return q.popleft() if q else None

    def consumer_finished(self, consumer_index: int) -> bool:
        with self._lock:
            return (self._finished_producers >= self.n_producers
                    and not self._queues[consumer_index])


class LocalExchangeSinkOperator(Operator):
    """Terminal operator of a producer pipeline
    (operator/exchange/LocalExchangeSinkOperator.java:31)."""

    def __init__(self, exchange: LocalExchange, producer_index: int,
                 names: Sequence[str]):
        self.exchange = exchange
        self.producer_index = producer_index
        self.names = list(names)

    def needs_input(self) -> bool:
        return (super().needs_input()
                and self.exchange.can_accept(self.producer_index))

    def add_input(self, batch: ColumnBatch) -> None:
        self.exchange.deposit(self.producer_index, batch.rename(self.names))

    def finish_input(self) -> None:
        super().finish_input()
        self.exchange.producer_finished()

    def is_finished(self) -> bool:
        return self.input_done


class LocalExchangeSourceOperator(Operator):
    """Source operator of a consumer pipeline
    (operator/exchange/LocalExchangeSourceOperator.java:27)."""

    def __init__(self, exchange: LocalExchange, consumer_index: int):
        self.exchange = exchange
        self.consumer_index = consumer_index
        self.input_done = True

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[ColumnBatch]:
        if self._closed:
            return None
        return self.exchange.poll(self.consumer_index)

    def is_finished(self) -> bool:
        return self._closed or self.exchange.consumer_finished(
            self.consumer_index)
