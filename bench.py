"""Benchmark: fused TPC-H Q1 kernel throughput on the available device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = device rows/sec over a single-thread numpy CPU implementation
of the same query measured in the same process (the reference publishes no
absolute numbers — BASELINE.json.published = {} — so the baseline is
self-measured, per SURVEY §6).

Env knobs: BENCH_SF (default 1.0), BENCH_ITERS (default 5).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def main() -> None:
    sf = float(os.environ.get("BENCH_SF", "1.0"))
    iters = int(os.environ.get("BENCH_ITERS", "5"))

    import jax
    import jax.numpy as jnp

    from trino_tpu.bench_kernels import Q1Batch, make_q1_inputs, q1_numpy, q1_step

    host = make_q1_inputs(sf)
    n = int(host.shipdate.shape[0])

    dev = Q1Batch(*[jax.device_put(jnp.asarray(c)) for c in host])
    # warmup / compile
    out = q1_step(dev)
    jax.block_until_ready(out)

    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = q1_step(dev)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    dt = float(np.median(times))
    rows_per_sec = n / dt

    t0 = time.perf_counter()
    q1_numpy(host)
    cpu_dt = time.perf_counter() - t0
    cpu_rows_per_sec = n / cpu_dt

    print(json.dumps({
        "metric": f"tpch_q1_sf{sf:g}_rows_per_sec",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / cpu_rows_per_sec, 3),
    }))


if __name__ == "__main__":
    main()
