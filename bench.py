"""Benchmark: TPC-H Q1 + Q3 through the FULL engine on the available device.

Unlike a kernel microbench, this drives parse -> plan -> optimize -> operators
(the same path `StandaloneQueryRunner` gives users), so it moves when the
engine regresses.  Data is staged into the memory connector first (CTAS via
the engine) so the timed region measures query execution over host-resident
tables — the moral equivalent of the reference's benchto harness reading
warmed Hive tables (testing/trino-benchto-benchmarks/.../tpch.yaml).

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}:
- value   = scanned input rows / median wall-clock, summed over Q1+Q3
- vs_baseline = speedup over the SAME engine running the SAME queries on an
  8-worker CPU DistributedQueryRunner in a subprocess (the self-measured CPU
  reference BASELINE.md mandates; the reference repo publishes no absolute
  numbers).
A bytes/s sanity line goes to stderr: scanned-bytes/s must stay below HBM
peak (~0.8 TB/s on v5e) or the measurement is rejected as bogus.

Env knobs: BENCH_SF (default 2; BENCH_SF=10 is the SF10 utilization profile
leg — per-query rows/s, rows/s/chip and GB/s land in the JSON for
BASELINE.md's honest-baseline tables), BENCH_ITERS (default 3),
BENCH_BASELINE_WORKERS (default 8), BENCH_SKIP_BASELINE=1 to skip.
An unusable accelerator backend falls back to JAX_PLATFORMS=cpu instead of
failing (subprocess device probe, same pattern as __graft_entry__).

Subcommands: ``--scan`` (ingest microbench), ``--ndv [1e3,1e4,...]``
(TRINO_TPU_HASH_IMPL hash-vs-sort NDV-ladder bake-off, see run_ndv_bench),
``--qps`` (two-tenant weighted-fair sustained-load harness + OOM drill,
see run_qps_bench; BENCH_QPS_DURATION/BENCH_QPS_SF/BENCH_QPS_CLIENTS),
``--warm`` (cache-plane cold/warm/warm-after-mutation ladder, see
run_warm_bench; BENCH_WARM_SF/BENCH_WARM_REPS), ``--adaptive`` (adaptive
execution on/off A/B over a skewed-key TPC-H variant and a mis-estimated
broadcast plan, see run_adaptive_bench; BENCH_ADAPTIVE_SF/
BENCH_ADAPTIVE_WORKERS), ``--hbo`` (history-based optimization second-run
leg over the same mis-estimated broadcast plan, see run_hbo_bench; same
env knobs as --adaptive).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

HBM_PEAK_BYTES_PER_SEC = 0.82e12  # v5e HBM ~819 GB/s
_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".jax_cache")

Q1 = """
select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
       sum(l_extendedprice) as sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
       avg(l_quantity) as avg_qty, avg(l_extendedprice) as avg_price,
       avg(l_discount) as avg_disc, count(*) as count_order
from lineitem where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus
"""

Q3 = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10
"""

QUERIES = {"q1": Q1, "q3": Q3}
TABLES = {"q1": ["lineitem"], "q3": ["customer", "orders", "lineitem"]}


def _ensure_backend() -> None:
    """Probe the configured JAX backend in a SUBPROCESS with a hard timeout
    (same pattern as __graft_entry__._devices_usable: a wedged TPU plugin
    hangs ``jax.devices()`` indefinitely and a libtpu/client mismatch only
    surfaces at device_put), and fall back to JAX_PLATFORMS=cpu instead of
    exiting rc=1 when the accelerator is unusable.  An explicit
    JAX_PLATFORMS choice is respected as-is."""
    if os.environ.get("JAX_PLATFORMS"):
        return
    code = (
        "import numpy as np\n"
        "import jax\n"
        "d = jax.devices()[0]\n"
        "jax.device_put(np.zeros(1), d).block_until_ready()\n"
    )
    try:
        ok = subprocess.run(
            [sys.executable, "-c", code], env=dict(os.environ),
            capture_output=True, timeout=60.0,
        ).returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        ok = False
    if not ok:
        print("bench: accelerator backend unusable; falling back to "
              "JAX_PLATFORMS=cpu", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"


def _enable_compile_cache() -> None:
    """Persist XLA compiles across bench processes (warmup dominates wall
    time on a tunneled device otherwise)."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass


def _stage_memory_tables(sf: float):
    """Generate TPC-H tables once and stage them in the memory connector as
    one consolidated batch per table (the warmed-table equivalent of the
    reference's benchto setup; big batches keep the per-batch dispatch and
    sync count off the measured path).  The big tables (orders/lineitem) are
    generated ON the device — on an accelerator the columns are born in HBM
    and staging never pushes row data through the host<->device tunnel; on
    the CPU backend the same vectorized XLA generator is still orders of
    magnitude faster than the per-row host page source (which made
    BENCH_SF=10 staging run for hours on the fallback)."""
    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.connectors.tpch import generate_table_device
    from trino_tpu.spi.batch import ColumnBatch
    from trino_tpu.spi.connector import TableSchema

    catalog = default_catalog(scale_factor=sf)
    tpch = catalog.connector("tpch")
    mem = catalog.connector("memory")
    for t in sorted({t for ts in TABLES.values() for t in ts}):
        schema = tpch.get_table_schema(t)
        cols = schema.column_names()
        batch = generate_table_device(tpch, t, cols)
        if batch is None:
            batches = []
            for s in tpch.get_splits(t, 4, 1):
                src = tpch.create_page_source(s, cols)
                while not src.is_finished():
                    b = src.get_next_batch()
                    if b is not None:
                        batches.append(b)
            batch = ColumnBatch.concat(batches)
        mem.create_table(TableSchema(t, schema.columns))
        mem.finish_insert(t, [[batch]])
        mem.pin_to_device(t)  # hot tables live in device memory
    return catalog


def _scan_stats(runner, sql: str) -> tuple[float, float]:
    """(rows, bytes) the plan's table scans read (post column pruning)."""
    from trino_tpu.planner.plan import TableScan

    rows = 0.0
    nbytes = 0.0

    def walk(node):
        nonlocal rows, nbytes
        if isinstance(node, TableScan):
            stats = runner.catalog.connector(node.catalog).get_table_statistics(
                node.table)
            r = stats.row_count
            rows += r
            nbytes += r * sum(
                __import__("numpy").dtype(t.storage_dtype).itemsize
                for t in node.output_types)
        for c in node.children:
            walk(c)

    walk(runner.create_plan(sql))
    return rows, nbytes


def _time_queries(runner, iters: int) -> dict[str, float]:
    """Median wall-clock per query (after one warmup compile run)."""
    import jax

    times: dict[str, float] = {}
    for name, sql in QUERIES.items():
        runner.execute(sql)  # warmup: compile every jitted program
        samples = []
        for _ in range(iters):
            t0 = time.perf_counter()
            r = runner.execute(sql)
            for c in r.batch.columns:  # force any device work to finish
                jax.block_until_ready(c.data)
            samples.append(time.perf_counter() - t0)
        samples.sort()
        times[name] = samples[len(samples) // 2]
    return times


def _build_qps_plane(catalog, workers: int = 2, root_slots: int = 4,
                     heavy_weight: int = 3, light_weight: int = 1,
                     memory_capacity=None):
    """Two-tenant serving plane: ONE weighted-fair DispatchManager + ONE
    ClusterMemoryManager shared by two runners whose sessions differ only in
    ``source`` — the selector routes heavy/light traffic into sibling groups
    competing for ``root_slots`` concurrency slots at weights 3:1."""
    from trino_tpu.execution.control import DispatchManager
    from trino_tpu.execution.distributed_runner import DistributedQueryRunner
    from trino_tpu.execution.resource_manager import (
        ClusterMemoryManager,
        ResourceGroup,
    )
    from trino_tpu.runner import Session

    root = ResourceGroup("global", hard_concurrency_limit=root_slots,
                         scheduling_policy="weighted_fair", max_queued=1000)
    root.subgroup("heavy", weight=heavy_weight,
                  hard_concurrency_limit=root_slots)
    root.subgroup("light", weight=light_weight,
                  hard_concurrency_limit=root_slots)
    dispatcher = DispatchManager(
        root, selector=lambda sql, s: getattr(s, "source", ""))
    mm = ClusterMemoryManager(capacity_bytes=memory_capacity)
    runners = {}
    for name in ("heavy", "light"):
        r = DistributedQueryRunner(
            catalog, worker_count=workers,
            session=Session(default_catalog="memory", source=name,
                            node_count=workers))
        r.dispatcher = dispatcher
        r.memory_manager = mm
        runners[name] = r
    return root, dispatcher, mm, runners


def _pct(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def _result_cache_off(fn):
    """The qps/OOM legs measure *execution* — admission, fair scheduling,
    the cluster kill path.  A served cached result would skip the very
    machinery under measurement, so the result tier is pinned off for the
    duration of the leg (plan/executable tiers stay on: their hits still
    execute)."""
    import functools

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        from trino_tpu.caching import result_cache

        with result_cache.disabled():
            return fn(*args, **kwargs)
    return wrapper


@_result_cache_off
def run_qps_sustained(duration_s: float, catalog, clients_per_group: int = 5,
                      sql: str = None) -> dict:
    """The sustained-load leg: closed-loop clients per tenant hammer the
    shared admission plane for ``duration_s``; returns completed-work
    counts, per-group latency/queue-wait percentiles, queue depth and kill
    counts.  Saturation (clients > root slots) is what makes the
    completed-work ratio track the 3:1 configured weights."""
    import threading

    from trino_tpu.telemetry import runtime as rt

    sql = sql or Q1
    root, dispatcher, mm, runners = _build_qps_plane(catalog)
    for r in runners.values():
        r.execute(sql)  # warmup: compile outside the measured window
    stop = threading.Event()
    done: dict[str, list] = {"heavy": [], "light": []}
    failed = {"heavy": 0, "light": 0}

    def client(group: str):
        r = runners[group]
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                r.execute(sql)
            except Exception:
                failed[group] += 1
                continue
            done[group].append(time.perf_counter() - t0)

    depth: list[int] = []

    def monitor():
        while not stop.is_set():
            depth.append(root.queued_total)
            time.sleep(0.05)

    threads = [threading.Thread(target=client, args=(g,), daemon=True)
               for g in ("heavy", "light") for _ in range(clients_per_group)]
    threads.append(threading.Thread(target=monitor, daemon=True))
    for t in threads:
        t.start()
    time.sleep(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=60)

    waits = {"heavy": [], "light": []}
    for q in rt.queries():
        g = q.resource_group.rsplit(".", 1)[-1]
        if g in waits:
            waits[g].append(q.queued_ms)
    out = {"duration_s": duration_s,
           "clients_per_group": clients_per_group,
           "weights": {"heavy": 3, "light": 1},
           "queue_depth_max": max(depth, default=0),
           "queue_depth_mean": round(sum(depth) / len(depth), 2)
           if depth else 0.0,
           "oom_kills": mm.oom_kills}
    for g in ("heavy", "light"):
        lat = sorted(done[g])
        qw = sorted(waits[g])
        out[g] = {"completed": len(lat), "failed": failed[g],
                  "latency_p50_ms": round(_pct(lat, 0.50) * 1e3, 1),
                  "latency_p99_ms": round(_pct(lat, 0.99) * 1e3, 1),
                  "queue_wait_p50_ms": round(_pct(qw, 0.50), 1),
                  "queue_wait_p99_ms": round(_pct(qw, 0.99), 1)}
    light = max(1, out["light"]["completed"])
    out["fairness_ratio"] = round(out["heavy"]["completed"] / light, 3)
    return out


@_result_cache_off
def run_qps_oom_drill(catalog, capacity_bytes: int = 64 << 20,
                      pressure_bytes: int = 256 << 20,
                      timeout_s: float = 60.0) -> dict:
    """The OOM-killer drill: a capped ClusterMemoryManager, one running
    query, and a synthetic worker snapshot attributing ``pressure_bytes``
    to it — the killer's actual input plane is worker /v1/status JSON, so
    injecting a snapshot exercises the real kill path end to end: the
    drain loop polls the handle, raises CLUSTER_OUT_OF_MEMORY, and a
    follow-up query completes once the pressure clears."""
    import threading

    from trino_tpu.spi.errors import TrinoError

    root, dispatcher, mm, runners = _build_qps_plane(
        catalog, memory_capacity=capacity_bytes)
    mm.enforce_interval_s = 0.0  # drill: enforce on every poll
    r = runners["heavy"]
    r.execute(Q1)  # warmup
    result: dict = {}

    def victim():
        try:
            for _ in range(2000):  # long enough for the kill to land
                r.execute(Q1)
            result["error"] = None
        except TrinoError as e:
            result["error"] = e.code.name
        except Exception as e:  # pragma: no cover - diagnostic
            result["error"] = f"{type(e).__name__}: {e}"

    th = threading.Thread(target=victim, daemon=True)
    th.start()
    # keep pressure on whichever query is registered right now until a kill
    # lands (a query finishing between sweeps takes its accounting with it)
    deadline = time.monotonic() + timeout_s
    killed = False
    while not killed and time.monotonic() < deadline:
        with mm._lock:
            live = list(mm._handles.values())
        if live:
            h = live[0]
            mm.update_worker("synthetic-pressure", {"tasks": {
                "t0": {"query_id": h.query_id,
                       "memory_reserved_bytes": pressure_bytes}}})
            mm.enforce()
            killed = h.killed
        time.sleep(0.005)
    th.join(timeout=timeout_s)
    hung = th.is_alive()
    # pressure clears with the worker snapshot; steady state must return
    mm.forget_worker("synthetic-pressure")
    post_ok = False
    if not hung:
        try:
            runners["light"].execute(Q1)
            post_ok = True
        except Exception:
            post_ok = False
    return {"capacity_bytes": capacity_bytes,
            "pressure_bytes": pressure_bytes,
            "victim_error": result.get("error"),
            "victim_hung": hung,
            "oom_kills": mm.oom_kills,
            "post_drill_query_ok": post_ok}


def run_qps_bench(duration_s: float = None, sf: float = None,
                  clients_per_group: int = None, write: bool = True) -> dict:
    """``bench.py --qps``: the multi-tenant serving benchmark.  Two resource
    groups at 3:1 weights under saturating closed-loop load (acceptance:
    completed-work ratio within +-25% of 3.0, bounded light-group queue
    wait), then the capped-memory OOM drill.  Writes BENCH_r08.json."""
    duration_s = duration_s if duration_s is not None else float(
        os.environ.get("BENCH_QPS_DURATION", "30"))
    sf = sf if sf is not None else float(
        os.environ.get("BENCH_QPS_SF", "0.05"))
    clients_per_group = clients_per_group or int(
        os.environ.get("BENCH_QPS_CLIENTS", "5"))
    _ensure_backend()
    _enable_compile_cache()
    catalog = _stage_memory_tables(sf)
    sustained = run_qps_sustained(duration_s, catalog,
                                  clients_per_group=clients_per_group)
    drill = run_qps_oom_drill(catalog)
    result = {
        "metric": f"qps_two_group_weighted_fair_sf{sf:g}",
        "value": sustained["fairness_ratio"],
        "unit": "heavy/light completed ratio (target 3.0 +-25%)",
        "sustained": sustained,
        "oom_drill": drill,
    }
    print(json.dumps(result))
    if write:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_r08.json"), "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
    return result


def _chaos_spec_drill() -> dict:
    """Speculation tail-cut acceptance: one injected TASK_STALL straggler
    on a leaf stage; TRINO_TPU_SPECULATION=1 must cut the wall to <=0.5x
    of the no-speculation run with identical rows and the loser provably
    cancelled (first-commit-wins — the row sets match exactly, so no
    double-commit)."""
    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.execution.distributed_runner import DistributedQueryRunner
    from trino_tpu.execution.failure_injector import (
        TASK_STALL,
        FailureInjector,
    )
    from trino_tpu.runner import Session

    sql = ("select l_returnflag, count(*), sum(l_quantity) from lineitem "
           "group by l_returnflag order by l_returnflag")
    prev = os.environ.get("TRINO_TPU_FUSED_STAGE")
    os.environ["TRINO_TPU_FUSED_STAGE"] = "0"  # leaf-only eligibility
    try:
        def once(spec: bool):
            inj = FailureInjector()
            # collectives off: a twin cannot join an in-flight all_to_all,
            # so collective-edge leaves are speculation-ineligible and the
            # drill would never speculate on a multi-device mesh
            r = DistributedQueryRunner(
                default_catalog(scale_factor=0.01), worker_count=4,
                session=Session(node_count=4, failure_injector=inj,
                                speculation=spec, use_collectives=False))
            leaf = [f for f in r.create_subplan(sql).all_fragments()
                    if not f.source_fragments][0]
            inj.inject(TASK_STALL, fragment_id=leaf.id, task_index=0,
                       attempt=0, stall_s=3.0)
            t0 = time.perf_counter()
            rows = r.execute(sql).rows()
            return time.perf_counter() - t0, rows, r

        wall_off, rows_off, _ = once(False)
        wall_on, rows_on, r = once(True)
    finally:
        if prev is None:
            os.environ.pop("TRINO_TPU_FUSED_STAGE", None)
        else:
            os.environ["TRINO_TPU_FUSED_STAGE"] = prev
    return {
        "wall_s_no_speculation": round(wall_off, 3),
        "wall_s_speculation": round(wall_on, 3),
        "ratio": round(wall_on / wall_off, 3),
        "rows_identical": sorted(rows_off) == sorted(rows_on),
        "speculative_starts": r.speculative_starts,
        "speculative_wins": r.speculative_wins,
        "pass": (wall_on <= 0.5 * wall_off
                 and sorted(rows_off) == sorted(rows_on)
                 and r.speculative_wins >= 1),
    }


def _chaos_rolling_restart_drill() -> dict:
    """Rolling-restart acceptance: drain every worker one at a time (real
    PUT /v1/shutdown + replacement) under sustained query load — zero
    queries lost."""
    import threading

    from trino_tpu.execution.remote import ProcessDistributedQueryRunner
    from trino_tpu.runner import Session
    from trino_tpu.testing.chaos import CATALOG_SPEC, _ENV, QUERY_MIX

    r = ProcessDistributedQueryRunner(
        CATALOG_SPEC, worker_count=2,
        session=Session(node_count=2, retry_policy="QUERY",
                        retry_initial_delay_s=0.01,
                        heartbeat_interval_s=0.2, drain_timeout_s=10.0),
        env_overrides=_ENV)
    stop = threading.Event()
    ok, failed = [], []

    def load():
        i = 0
        while not stop.is_set():
            sql = QUERY_MIX[i % len(QUERY_MIX)]
            i += 1
            try:
                r.execute(sql).rows()
                ok.append(sql)
            except Exception as e:  # noqa: BLE001 - any loss is a failure
                failed.append(f"{type(e).__name__}: {e}")

    try:
        r.execute(QUERY_MIX[0]).rows()  # warm up before the restarts
        th = threading.Thread(target=load, daemon=True)
        th.start()
        summaries = r.rolling_restart()
        time.sleep(1.0)
        stop.set()
        th.join(60)
        states = r.execute(
            "select worker, state from system.runtime.workers").rows()
    finally:
        r.close()
    return {
        "workers_drained": len(summaries),
        "escalated": sum(1 for s in summaries if s["escalated"]),
        "queries_completed": len(ok),
        "queries_lost": len(failed),
        "failures": failed[:5],
        "final_worker_states": sorted(states),
        "pass": (len(failed) == 0 and len(ok) > 0
                 and sum(1 for _, st in states if st == "ACTIVE") == 2),
    }


def run_fte_chaos_bench(write: bool = True) -> dict:
    """``bench.py --chaos-fte`` (also appended to ``--chaos``): the FTE
    chaos-certification leg for PR 15.  A seeded fault campaign over
    ``retry_policy="TASK"`` — the streaming menu plus SPOOL_CORRUPTION
    bit flips on committed spool files — followed by the coordinator
    kill -9 drill: SIGKILL mid-query, restart, resume from the query-state
    WAL with zero re-execution of committed attempts.  Acceptance is the
    PR-9 bar (100%% of queries accounted, zero hangs) plus the drill's
    ``pass``.  Writes BENCH_r15.json."""
    n = int(os.environ.get("BENCH_FTE_CHAOS_SCENARIOS", "10"))
    seed = int(os.environ.get("BENCH_FTE_CHAOS_SEED", "1515"))
    _ensure_backend()
    _enable_compile_cache()

    from trino_tpu.telemetry.metrics import REGISTRY
    from trino_tpu.testing.chaos import run_coordinator_kill_drill, run_fte_chaos

    print(f"fte chaos leg: {n} scenarios from seed {seed}", file=sys.stderr)
    t0 = time.perf_counter()
    soak = run_fte_chaos(n_scenarios=n, base_seed=seed)
    soak_wall = time.perf_counter() - t0
    print("coordinator kill -9 drill", file=sys.stderr)
    t0 = time.perf_counter()
    drill = run_coordinator_kill_drill()
    drill_wall = time.perf_counter() - t0
    drill_out = {k: v for k, v in drill.items() if k != "rows"}
    drill_out["n_rows"] = len(drill.get("rows") or [])

    accounted = (soak["n_queries"] - soak["hangs"] - soak["unexpected"]
                 ) / max(soak["n_queries"], 1)
    result = {
        "metric": f"fte_chaos_{n}_scenarios_accounted_fraction",
        "value": round(accounted, 4),
        "unit": "fraction of FTE queries oracle-correct or correctly "
                "classified (target 1.0, zero hangs)",
        "soak_wall_s": round(soak_wall, 1),
        "drill_wall_s": round(drill_wall, 1),
        "soak": soak,
        "coordinator_kill_drill": drill_out,
        "metrics": {k: v for k, v in REGISTRY.snapshot().items()
                    if k.startswith("trino_fte_")},
    }
    print(json.dumps({k: v for k, v in result.items() if k != "soak"}))
    if write:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_r15.json"), "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
    return result


def run_ha_bench(write: bool = True) -> dict:
    """``bench.py --ha``: the HA control-plane certification (PR 20).
    Writes BENCH_r20.json.  Three legs:

    1. **Lease takeover under load**: a two-coordinator fleet behind the
       stateless front tier (server/front_tier.py) at steady QPS; one
       coordinator holds an unrescuable in-flight FTE query and is killed
       -9.  The peer must claim the lease, adopt the query, and finish it
       under its ORIGINAL id through the tier's reroute path — zero lost
       queries, zero re-execution of committed attempts, and post-takeover
       p99 < 5x steady p99.
    2. **Elastic autoscaling**: a real process-worker cluster under
       memory-capped admission; the WorkerAutoscaler must add a worker
       while ``trino_admission_queued_seconds`` accumulates and drain one
       (zero-loss PUT /v1/shutdown) once the pressure passes.
    3. **Legacy parity**: with TRINO_TPU_HA=0 the chaos query mix is
       bit-for-bit oracle-correct, no HA state appears on disk, and no
       trino_ha_* activity is recorded.
    """
    import shutil
    import signal
    import statistics
    import tempfile
    import threading

    _ensure_backend()
    _enable_compile_cache()

    from trino_tpu.execution import ha as ha_mod
    from trino_tpu.execution import query_state
    from trino_tpu.telemetry import metrics as tm
    from trino_tpu.testing import chaos
    from trino_tpu.testing.chaos import _http_json

    repo = os.path.dirname(os.path.abspath(__file__))
    steady_n = int(os.environ.get("BENCH_HA_QUERIES", "8"))
    lease_ttl = float(os.environ.get("BENCH_HA_LEASE_TTL_S", "2"))

    # ---------------------------------------- leg 1: takeover under load
    print("ha leg 1: lease takeover under steady QPS", file=sys.stderr)
    work = tempfile.mkdtemp(prefix="trino-tpu-ha-bench-")
    ha_root = os.path.join(work, "ha")
    base_env = dict(os.environ)
    base_env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "TRINO_TPU_HA": "1",
        "TRINO_TPU_HA_DIR": ha_root,
        "TRINO_TPU_HA_LEASE_TTL_S": str(lease_ttl),
        "TRINO_TPU_HA_HEARTBEAT_S": "0.5",
        "TRINO_TPU_QUERY_STATE": "1",
        "TRINO_TPU_SPOOL_DIR": os.path.join(work, "spool"),
        "TRINO_TPU_JOURNAL_DIR": os.path.join(work, "journal"),
        "TRINO_TPU_RESULT_CACHE": "0",
        "PYTHONPATH": repo + os.pathsep + base_env.get("PYTHONPATH", ""),
    })
    child_cmd = [sys.executable, "-c",
                 "from trino_tpu.testing.chaos import _ha_coordinator_child;"
                 " _ha_coordinator_child()"]

    def _boot(node, extra):
        port_file = os.path.join(work, f"port-{node}")
        env = {**base_env, "TRINO_TPU_HA_NODE_ID": node,
               "TRINO_TPU_QUERY_STATE_DIR":
                   os.path.join(ha_root, "wal", node),
               "CHAOS_PORT_FILE": port_file, **extra}
        proc = subprocess.Popen(child_cmd, env=env, cwd=repo)
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(f"HA child {node} died at boot")
            if os.path.exists(port_file):
                with open(port_file, encoding="utf-8") as f:
                    return proc, int(f.read().strip())
            time.sleep(0.1)
        proc.kill()
        raise TimeoutError(f"HA child {node} never wrote its port")

    def _poll_tier(tier_port, first, timeout_s=120.0):
        out, rows = first, list(first.get("data", []))
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            state = out.get("stats", {}).get("state")
            nxt = out.get("nextUri")
            if state == "FAILED" or (state == "FINISHED" and not nxt):
                return state, rows
            out = _http_json(
                "GET", f"http://127.0.0.1:{tier_port}{nxt}", timeout=60.0)
            rows += out.get("data", [])
        return "TIMEOUT", rows

    def _run_via_tier(tier_port, sql):
        t0 = time.monotonic()
        first = _http_json("POST",
                           f"http://127.0.0.1:{tier_port}/v1/statement",
                           sql.encode("utf-8"), timeout=60.0)
        state, _rows = _poll_tier(tier_port, first)
        return state, time.monotonic() - t0

    from trino_tpu.server.front_tier import FrontTier

    leg1: dict = {}
    proc_a = proc_b = None
    tier = None
    try:
        proc_a, port_a = _boot("coordA", {"CHAOS_STALL_S": "300"})
        proc_b, port_b = _boot("coordB", {})
        tier = FrontTier(root=ha_root, ttl=lease_ttl, retry_s=30.0).start()
        tier_port = tier.address[1]

        # the pinned in-flight query: eats coordA's one-shot stall
        sub = _http_json("POST",
                         f"http://127.0.0.1:{port_a}/v1/statement",
                         chaos._DRILL_SQL.encode("utf-8"))
        drill_qid = sub["id"]
        wal_a = os.path.join(ha_root, "wal", "coordA", drill_qid + ".wal")
        pq = None
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            pq = query_state.load(wal_a)
            if pq is not None and len(pq.committed) >= 1:
                break
            time.sleep(0.1)
        if pq is None or not pq.committed:
            raise TimeoutError("no committed attempt before the kill")
        starts_at_kill = dict(pq.attempt_counts)
        committed_at_kill = dict(pq.committed)

        steady = [_run_via_tier(tier_port, sql) for sql in
                  (chaos.QUERY_MIX * 3)[:steady_n]]
        assert all(s == "FINISHED" for s, _ in steady), steady

        reroutes_before = tm.HA_REROUTES.value()
        t_kill = time.monotonic()
        os.kill(proc_a.pid, signal.SIGKILL)
        proc_a.wait(timeout=30)
        # takeover: coordB claims the expired lease + WAL custody
        lease_a = os.path.join(ha_root, "coordinators", "coordA.json")
        deadline = time.monotonic() + 60.0
        while os.path.exists(lease_a) and time.monotonic() < deadline:
            time.sleep(0.1)
        takeover_s = time.monotonic() - t_kill

        # the in-flight query finishes under its original id, polled
        # through the tier (reroute: the hash owner is gone)
        first = _http_json(
            "GET",
            f"http://127.0.0.1:{tier_port}/v1/statement/{drill_qid}/0",
            timeout=60.0)
        drill_state, drill_rows = _poll_tier(tier_port, first)

        post = [_run_via_tier(tier_port, sql) for sql in
                (chaos.QUERY_MIX * 3)[:steady_n]]

        wal_root = os.path.join(ha_root, "wal")
        claimed = [d for d in sorted(os.listdir(wal_root))
                   if d.startswith("coordA.claimed-coordB-")]
        final = query_state.load(os.path.join(
            wal_root, claimed[0], drill_qid + ".wal")) if claimed else None
        re_executed = {}
        if final is not None:
            re_executed = {
                f"f{fid}_t{t}": final.attempt_counts.get((fid, t), 0)
                - starts_at_kill.get((fid, t), 0)
                for (fid, t) in committed_at_kill
                if final.attempt_counts.get((fid, t), 0)
                > starts_at_kill.get((fid, t), 0)}

        steady_walls = sorted(w for _s, w in steady)
        post_walls = sorted(w for _s, w in post)

        def p99(walls):
            return walls[min(len(walls) - 1,
                             int(0.99 * len(walls)))] if walls else 0.0

        leg1 = {
            "steady_queries": len(steady),
            "post_queries": len(post),
            "lost_queries": sum(1 for s, _ in steady + post
                                if s != "FINISHED")
            + (0 if drill_state == "FINISHED" else 1),
            "in_flight_state": drill_state,
            "in_flight_rows": len(drill_rows),
            "committed_at_kill": len(committed_at_kill),
            "committed_reexecuted": re_executed,
            "claimed_dirs": claimed,
            "takeover_s": round(takeover_s, 2),
            "tier_reroutes": tm.HA_REROUTES.value() - reroutes_before,
            "steady_p50_s": round(statistics.median(steady_walls), 3),
            "steady_p99_s": round(p99(steady_walls), 3),
            "post_p99_s": round(p99(post_walls), 3),
            "p99_ratio": round(p99(post_walls)
                               / max(p99(steady_walls), 1e-9), 2),
        }
        # NB: tier_reroutes is informational — in a 2-member fleet the
        # claimant IS the post-death rehash owner, so the probe path
        # (covered by tests/test_ha.py) rarely fires here
        leg1["pass"] = (leg1["lost_queries"] == 0
                        and drill_state == "FINISHED"
                        and re_executed == {} and bool(claimed)
                        and leg1["p99_ratio"] < 5.0)
    finally:
        if tier is not None:
            tier.stop()
        for p in (proc_a, proc_b):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=15)
        shutil.rmtree(work, ignore_errors=True)

    # -------------------------------------------- leg 2: elastic workers
    print("ha leg 2: worker autoscaling", file=sys.stderr)
    from trino_tpu.execution.remote import ProcessDistributedQueryRunner
    from trino_tpu.runner import Session
    from trino_tpu.server.protocol import TrinoTpuServer

    # query_concurrency=1: concurrent clients genuinely queue at the
    # resource-group gate, which records trino_admission_queued_seconds —
    # the autoscaler's pressure signal
    session = Session(node_count=1, retry_policy="QUERY",
                      query_concurrency=1)
    runner = ProcessDistributedQueryRunner(
        chaos.CATALOG_SPEC, worker_count=1, session=session,
        env_overrides=chaos._ENV)
    srv = TrinoTpuServer(runner, max_concurrent=4)
    srv.start()
    asc = ha_mod.WorkerAutoscaler(runner, min_workers=1, max_workers=2,
                                  queue_s=0.2, idle_rounds=3,
                                  interval_s=0.5)
    leg2: dict = {}
    try:
        host, port = srv.address
        results: list = []

        def client(n):
            for i in range(n):
                sql = chaos.QUERY_MIX[i % len(chaos.QUERY_MIX)]
                first = _http_json(
                    "POST", f"http://{host}:{port}/v1/statement",
                    sql.encode("utf-8"), timeout=120.0)
                out, state = first, None
                deadline = time.monotonic() + 120.0
                while time.monotonic() < deadline:
                    state = out.get("stats", {}).get("state")
                    nxt = out.get("nextUri")
                    if state == "FAILED" or (state == "FINISHED"
                                             and not nxt):
                        break
                    out = _http_json(
                        "GET", f"http://{host}:{port}{nxt}", timeout=60.0)
                results.append(state)

        asc.start()
        workers_before = len(runner.workers)
        clients = [threading.Thread(target=client, args=(4,))
                   for _ in range(3)]
        for c in clients:
            c.start()
        for c in clients:
            c.join()
        workers_peak = max([workers_before]
                           + [e[1] for e in asc.events if e[0] == "up"])
        # pressure gone: the idle streak must drain back to the floor
        deadline = time.monotonic() + 30.0
        while len(runner.workers) > 1 and time.monotonic() < deadline:
            time.sleep(0.2)
        workers_after = len(runner.workers)
        asc.stop()
        queued_snap = tm.ADMISSION_QUEUED_SECONDS.snapshot()
        leg2 = {
            "queries": len(results),
            "lost_queries": sum(1 for s in results if s != "FINISHED"),
            "workers_before": workers_before,
            "workers_peak": workers_peak,
            "workers_after": workers_after,
            "events": [list(e) for e in asc.events],
            "admission_queued_count": queued_snap["count"],
            "admission_queued_sum_s": round(queued_snap["sum"], 3),
        }
        leg2["pass"] = (leg2["lost_queries"] == 0
                        and workers_peak == 2 and workers_after == 1
                        and any(e[0] == "up" for e in asc.events)
                        and any(e[0] == "down" for e in asc.events))
    finally:
        asc.stop()
        srv.stop()
        runner.close()

    # ----------------------------------------------- leg 3: legacy parity
    print("ha leg 3: TRINO_TPU_HA=0 parity", file=sys.stderr)
    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.execution.distributed_runner import DistributedQueryRunner
    from trino_tpu.testing.oracle import assert_same_rows

    assert os.environ.get("TRINO_TPU_HA", "0") in ("", "0"), \
        "leg 3 must run with HA off"
    ha_counters_before = {
        k: v["value"] for k, v in tm.REGISTRY.snapshot().items()
        if k.startswith("trino_ha_") and v["kind"] == "counter"
        and k != "trino_ha_reroutes_total"}  # leg 1's tier ran in-process
    expected = chaos.build_expected()
    legacy = DistributedQueryRunner(default_catalog(scale_factor=0.01),
                                    worker_count=2,
                                    session=Session(node_count=2))
    mismatches = 0
    for sql in chaos.QUERY_MIX:
        r1 = legacy.execute(sql).rows()
        r2 = legacy.execute(sql).rows()
        try:
            assert_same_rows(r1, expected[sql], ordered=False)
            assert_same_rows(r2, expected[sql], ordered=False)
        except AssertionError:
            mismatches += 1
    ha_counters_after = {
        k: v["value"] for k, v in tm.REGISTRY.snapshot().items()
        if k.startswith("trino_ha_") and v["kind"] == "counter"
        and k != "trino_ha_reroutes_total"}
    leg3 = {
        "queries": 2 * len(chaos.QUERY_MIX),
        "mismatches": mismatches,
        "ha_counter_deltas": {
            k: ha_counters_after[k] - ha_counters_before.get(k, 0)
            for k in ha_counters_after},
        "pass": mismatches == 0 and all(
            ha_counters_after[k] == ha_counters_before.get(k, 0)
            for k in ha_counters_after),
    }

    result = {
        "metric": "ha_takeover_p99_ratio",
        "value": leg1.get("p99_ratio"),
        "unit": "post-takeover p99 / steady p99 (target < 5.0; zero lost, "
                "zero re-executed committed attempts)",
        "takeover": leg1,
        "autoscaler": leg2,
        "legacy_parity": leg3,
        "pass": bool(leg1.get("pass") and leg2.get("pass")
                     and leg3.get("pass")),
        "metrics": {k: v for k, v in tm.REGISTRY.snapshot().items()
                    if k.startswith("trino_ha_")},
    }
    print(json.dumps({k: v for k, v in result.items()
                      if k not in ("metrics",)}))
    if write:
        with open(os.path.join(repo, "BENCH_r20.json"), "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
    return result


def run_chaos_bench(write: bool = True) -> dict:
    """``bench.py --chaos``: the chaos-certification soak.  A seeded
    randomized fault-injection campaign (trino_tpu/testing/chaos.py) over
    in-process and real-process clusters, plus the two acceptance drills
    (speculation tail-cut, rolling restart).  Writes BENCH_r09.json."""
    n = int(os.environ.get("BENCH_CHAOS_SCENARIOS", "25"))
    seed = int(os.environ.get("BENCH_CHAOS_SEED", "1009"))
    _ensure_backend()
    _enable_compile_cache()

    from trino_tpu.telemetry.metrics import REGISTRY
    from trino_tpu.testing.chaos import run_chaos

    print(f"chaos soak: {n} scenarios from seed {seed}", file=sys.stderr)
    t0 = time.perf_counter()
    soak = run_chaos(n_scenarios=n, base_seed=seed)
    soak_wall = time.perf_counter() - t0
    print("speculation tail-cut drill", file=sys.stderr)
    spec = _chaos_spec_drill()
    print("rolling-restart drill", file=sys.stderr)
    rolling = _chaos_rolling_restart_drill()

    accounted = (soak["n_queries"] - soak["hangs"] - soak["unexpected"]
                 ) / max(soak["n_queries"], 1)
    result = {
        "metric": f"chaos_soak_{n}_scenarios_accounted_fraction",
        "value": round(accounted, 4),
        "unit": "fraction of queries oracle-correct or correctly classified"
                " (target 1.0, zero hangs)",
        "soak_wall_s": round(soak_wall, 1),
        "soak": soak,
        "speculation_drill": spec,
        "rolling_restart_drill": rolling,
        "metrics": {k: v for k, v in REGISTRY.snapshot().items()
                    if k.startswith(("trino_speculative", "trino_drains",
                                     "trino_blacklisted"))},
    }
    print(json.dumps({k: v for k, v in result.items() if k != "soak"}))
    if write:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_r09.json"), "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
    return result


def run_warm_bench(write: bool = True) -> dict:
    """``bench.py --warm``: the repeated-traffic cold/warm ladder for the
    three-tier cache plane (trino_tpu/caching/).  Three legs over the Q1+Q3
    mix:

    - **cold** — empty caches: parse -> plan -> optimize -> compile -> run.
    - **warm** — identical texts re-submitted: Tier A skips planning, Tier C
      serves the versioned result without touching the executors.
      Acceptance: warm p50 at least 10x under cold p50.
    - **warm-after-mutation** — INSERT into lineitem, re-run: every row set
      must match a cache-disabled oracle run (the result tier re-validates
      on the bumped table version; a stale serve fails the bench).

    Env knobs: BENCH_WARM_SF (default 0.05), BENCH_WARM_REPS (default 20).
    Writes BENCH_r12.json with p50/p99 per leg and per-tier hit rates."""
    sf = float(os.environ.get("BENCH_WARM_SF", "0.05"))
    reps = int(os.environ.get("BENCH_WARM_REPS", "20"))
    _ensure_backend()
    _enable_compile_cache()

    import jax

    from trino_tpu import caching
    from trino_tpu.runner import Session, StandaloneQueryRunner

    caching.reset_for_test()
    catalog = _stage_memory_tables(sf)
    runner = StandaloneQueryRunner(
        catalog, session=Session(default_catalog="memory", splits_per_node=1))

    def timed(sql: str):
        t0 = time.perf_counter()
        r = runner.execute(sql)
        for c in r.batch.columns:  # force any device work to finish
            jax.block_until_ready(c.data)
        return (time.perf_counter() - t0) * 1e3, r

    def oracle_rows(sql: str):
        """The same query with Tier A/C disabled — the staleness oracle."""
        os.environ["TRINO_TPU_PLAN_CACHE"] = "0"
        os.environ["TRINO_TPU_RESULT_CACHE"] = "0"
        try:
            return runner.execute(sql).rows()
        finally:
            del os.environ["TRINO_TPU_PLAN_CACHE"]
            del os.environ["TRINO_TPU_RESULT_CACHE"]

    # leg 1 — cold: first submission of each text
    cold_ms = {name: round(timed(sql)[0], 2) for name, sql in QUERIES.items()}

    # leg 2 — warm: identical texts, reps times each
    warm_samples: list[float] = []
    warm_rows: dict[str, list] = {}
    for _ in range(reps):
        for name, sql in QUERIES.items():
            ms, r = timed(sql)
            warm_samples.append(ms)
            warm_rows[name] = r.rows()
    stale = any(warm_rows[name] != oracle_rows(sql)
                for name, sql in QUERIES.items())

    # leg 3 — mutation: bump lineitem (Q1 and Q3 both scan it), re-run
    runner.execute("insert into lineitem select * from lineitem "
                   "where l_orderkey = 1")
    post_ms: dict[str, float] = {}
    for name, sql in QUERIES.items():
        ms, r = timed(sql)
        post_ms[name] = round(ms, 2)
        if r.rows() != oracle_rows(sql):
            stale = True

    tiers = {}
    for row in caching.cache_rows():
        total = row["hits"] + row["misses"]
        tiers[row["name"]] = dict(
            row, hit_rate=round(row["hits"] / total, 3) if total else 0.0)

    warm_samples.sort()
    cold_sorted = sorted(cold_ms.values())
    cold_p50 = _pct(cold_sorted, 0.5)
    warm_p50 = _pct(warm_samples, 0.5)
    speedup = cold_p50 / warm_p50 if warm_p50 else 0.0
    result = {
        "metric": f"warm_path_speedup_p50_sf{sf:g}",
        "value": round(speedup, 1),
        "unit": "cold p50 / warm p50 wall (target >= 10x, no stale serve)",
        "pass_10x": speedup >= 10.0,
        "stale_serve": stale,
        "cold_ms": cold_ms,
        "warm_p50_ms": round(warm_p50, 3),
        "warm_p99_ms": round(_pct(warm_samples, 0.99), 3),
        "warm_after_mutation_ms": post_ms,
        "tiers": tiers,
    }
    print(json.dumps(result))
    if write:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_r12.json"), "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
    return result


# --adaptive leg 1: ~80% of the probe rows collapse onto ONE join key, so a
# static hash-partitioned join lands most of the work on a single task; the
# runtime skew split fans that key out across several probe tasks.  count and
# a DECIMAL sum only: both are exact and summation-order independent, so the
# off/on row comparison is bit-for-bit even though the split reorders pages.
# The sum spans both join sides so the iterative optimizer cannot compact
# the heavy key away with a pre-join partial aggregation
_ADAPTIVE_SKEW_SQL = """
select count(*) n, sum(p.o_totalprice + b.c_acctbal) s
from (select case when o_orderkey % 5 < 4 then 1
             else o_custkey end as k, o_totalprice from orders) p
join (select c_custkey, c_acctbal from customer) b on p.k = b.c_custkey
"""

# --adaptive leg 2: a genuine optimizer mis-estimate.  The four always-true
# range conjuncts each get the 0.4 one-sided-range selectivity from
# _conjunct_selectivity, so the optimizer estimates the orders subquery at
# 0.4^4 = 2.6% of its true size, makes it the smallest relation, and
# BROADCASTs it as the build side — every task re-builds the full 150k*sf-row
# hash table.  The runtime flip to PARTITIONED splits the build 1/n per task
# (a WORK reduction, visible even on a single-core host)
_ADAPTIVE_WRONG_SQL = """
select c.c_mktsegment, count(*) n, sum(o.o_totalprice) s
from customer c
join (select o_custkey, o_totalprice from orders
      where o_orderkey > -1 and o_orderkey > -2
        and o_orderkey > -3 and o_orderkey > -4) o
  on c.c_custkey = o.o_custkey
group by c.c_mktsegment order by c.c_mktsegment
"""


@_result_cache_off
def _adaptive_ab(sql: str, sf: float, workers: int, iters: int,
                 env: dict, on_session_kw: dict) -> dict:
    """One A/B leg: median wall for adaptive=0 vs adaptive=1 on a fresh
    runner each, identical (sorted) rows required, decision tags captured
    from the telemetry record of the adaptive run."""
    from trino_tpu import caching
    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.execution.distributed_runner import DistributedQueryRunner
    from trino_tpu.runner import Session
    from trino_tpu.telemetry import runtime as rt

    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        out: dict = {}
        rows: dict[str, list] = {}
        for mode, kw in (("off", {"adaptive": "0"}),
                         ("on", dict(on_session_kw, adaptive="1"))):
            caching.reset_for_test()
            r = DistributedQueryRunner(
                default_catalog(scale_factor=sf), worker_count=workers,
                session=Session(node_count=workers, **kw))
            r.execute(sql)  # warmup: compile every jitted program
            samples = []
            for _ in range(iters):
                t0 = time.perf_counter()
                res = r.execute(sql)
                samples.append(time.perf_counter() - t0)
            samples.sort()
            rows[mode] = sorted(res.rows())
            out[f"wall_s_{mode}"] = round(samples[len(samples) // 2], 3)
            if mode == "on":
                out["decisions"] = rt.queries()[-1].adaptive_decisions
        out["speedup"] = round(out["wall_s_off"] / max(out["wall_s_on"],
                                                       1e-9), 2)
        out["rows_identical"] = rows["off"] == rows["on"]
        return out
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def run_adaptive_bench(write: bool = True) -> dict:
    """``bench.py --adaptive``: the adaptive-execution acceptance A/B.

    Two legs, each adaptive=1 vs the bit-for-bit legacy adaptive=0 on the
    same data and plan inputs:

    - **skewed_key** — ~80% of probe rows on one join key, static plan
      forced PARTITIONED: the heavy partition serializes the legacy run;
      the runtime skew split must cut wall by >= 2x.  A split moves no
      work, it only balances it, so the wall target needs >= ``workers``
      usable cores; on a smaller host the leg is judged on the measured
      trino_adaptive_skew_imbalance_ratio gauge (max partition weight
      before/after — exactly what a parallel host converts to wall) and
      the JSON records which criterion applied.
    - **wrong_side_broadcast** — a selectivity mis-estimate (stacked
      always-true range conjuncts) broadcasts the big build side; the
      runtime flip to PARTITIONED must cut wall by >= 1.5x.  The flip is
      a work reduction (n duplicate hash builds -> 1), so the wall
      target holds on any host.

    Env knobs: BENCH_ADAPTIVE_SF (default 0.3), BENCH_ADAPTIVE_WORKERS
    (default 4), BENCH_ITERS (default 3).  Writes BENCH_r13.json."""
    sf = float(os.environ.get("BENCH_ADAPTIVE_SF", "0.3"))
    workers = int(os.environ.get("BENCH_ADAPTIVE_WORKERS", "4"))
    iters = int(os.environ.get("BENCH_ITERS", "3"))
    _ensure_backend()
    _enable_compile_cache()

    from trino_tpu.telemetry import metrics as tm
    from trino_tpu.telemetry.metrics import REGISTRY

    cores = len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity") \
        else (os.cpu_count() or 1)
    print(f"adaptive A/B: sf={sf:g} workers={workers} cores={cores}",
          file=sys.stderr)
    # threshold=1 byte: the tiny build must NOT flip to broadcast, so the
    # leg isolates the skew split (a broadcast flip would also fix skew,
    # but it is leg 2's mechanism)
    skew = _adaptive_ab(
        _ADAPTIVE_SKEW_SQL, sf, workers, iters,
        env={"TRINO_TPU_BROADCAST_ROW_LIMIT": "0"},
        on_session_kw={"broadcast_threshold_bytes": 1, "skew_factor": 1.2})
    skew["imbalance_ratio"] = round(tm.ADAPTIVE_SKEW_IMBALANCE.value(), 2)
    print(f"skewed_key: {skew}", file=sys.stderr)
    wrong = _adaptive_ab(
        _ADAPTIVE_WRONG_SQL, sf, workers, iters,
        env={}, on_session_kw={"broadcast_threshold_bytes": 1 << 20})
    print(f"wrong_side_broadcast: {wrong}", file=sys.stderr)

    # wall-clock is the skew criterion when the host can actually run the
    # tasks in parallel; a 1-core container cannot turn load balance into
    # wall, so there the sketch-measured imbalance ratio (what a parallel
    # host realises) is the honest stand-in — recorded either way
    skew_on_wall = cores >= workers
    skew_ok = (skew["rows_identical"] and "skew_split" in skew["decisions"]
               and (skew["speedup"] >= 2.0 if skew_on_wall
                    else skew["imbalance_ratio"] >= 2.0))
    result = {
        "metric": f"adaptive_skew_split_speedup_sf{sf:g}",
        "value": skew["speedup"],
        "unit": "adaptive=0 wall / adaptive=1 wall "
                "(skew target >= 2x, wrong-broadcast target >= 1.5x)",
        "workers": workers,
        "iters": iters,
        "cores": cores,
        "skew_criterion": ("wall_speedup >= 2.0" if skew_on_wall else
                           "imbalance_ratio >= 2.0 (host has fewer cores "
                           "than workers; wall cannot see load balance)"),
        "skewed_key": skew,
        "wrong_side_broadcast": wrong,
        "pass": (skew_ok
                 and wrong["speedup"] >= 1.5 and wrong["rows_identical"]
                 and "flip_to_partitioned" in wrong["decisions"]),
        "metrics": {k: v for k, v in REGISTRY.snapshot().items()
                    if k.startswith("trino_adaptive")},
    }
    print(json.dumps(result))
    if write:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_r13.json"), "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
    return result


def _hbo_walk(node):
    yield node
    for c in node.children:
        yield from _hbo_walk(c)


def _hbo_build_side(runner, sql: str) -> dict:
    """Plan (no execution) and report the sole join's distribution plus
    which base tables feed its build (right) side, following remote
    exchanges across fragments."""
    from trino_tpu.planner.plan import Join, RemoteSource, TableScan

    frags = runner.create_subplan(sql).all_fragments()
    by_id = {f.id: f for f in frags}
    join = next(n for f in frags for n in _hbo_walk(f.root)
                if isinstance(n, Join))

    def tables(node, seen):
        out = set()
        for n in _hbo_walk(node):
            if isinstance(n, TableScan):
                out.add(n.table)
            elif isinstance(n, RemoteSource) and n.fragment_id not in seen:
                seen.add(n.fragment_id)
                out |= tables(by_id[n.fragment_id].root, seen)
        return out

    return {"distribution": join.distribution,
            "build_tables": sorted(tables(join.right, set()))}


@_result_cache_off
def _hbo_second_run(sql: str, sf: float, workers: int, iters: int) -> dict:
    """Three runs of the BENCH_r13 wrong-side-broadcast leg against one
    isolated history journal:

    - **static** — HBO=0, adaptive=0: the mis-estimated BROADCAST plan
      runs uncorrected (reference floor; records nothing).
    - **run1** — HBO=1, adaptive=1: the first execution still plans
      BROADCAST (empty history), the runtime flip corrects it at the
      activation barrier AND the observed stats are journaled at query
      end.
    - **run2** — HBO=1, adaptive=0: a fresh runner re-plans from history
      and must choose PARTITIONED up front — no runtime correction left.
    """
    import tempfile

    from trino_tpu import caching
    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.execution.distributed_runner import DistributedQueryRunner
    from trino_tpu.planner.history import reset_for_test as history_reset
    from trino_tpu.planner.iterative.driver import last_report
    from trino_tpu.planner.plan import Join
    from trino_tpu.runner import Session
    from trino_tpu.telemetry import runtime as rt

    env = {
        "TRINO_TPU_JOURNAL_DIR": tempfile.mkdtemp(prefix="hbo_bench_"),
        # plan-time history and the adaptive activation barrier compare
        # observed build bytes against the SAME threshold: 1 MiB, far
        # under the real orders build side at this scale factor
        "TRINO_TPU_BROADCAST_THRESHOLD_BYTES": str(1 << 20),
    }
    saved = {k: os.environ.get(k) for k in list(env) + ["TRINO_TPU_HBO"]}
    os.environ.update(env)
    try:
        out: dict = {}
        rows: dict[str, list] = {}

        def fresh_runner(hbo: str, adaptive: str):
            os.environ["TRINO_TPU_HBO"] = hbo
            caching.reset_for_test()
            history_reset()
            return DistributedQueryRunner(
                default_catalog(scale_factor=sf), worker_count=workers,
                session=Session(node_count=workers, adaptive=adaptive))

        def timed(r, name: str) -> None:
            samples = []
            for _ in range(iters):
                t0 = time.perf_counter()
                res = r.execute(sql)
                samples.append(time.perf_counter() - t0)
            samples.sort()
            rows[name] = sorted(res.rows())
            out[f"wall_s_{name}"] = round(samples[len(samples) // 2], 3)

        # static floor: wrong BROADCAST, nothing corrects it, no recording
        r = fresh_runner(hbo="0", adaptive="0")
        out["static_plan"] = _hbo_build_side(r, sql)
        r.execute(sql)  # warmup: compile every jitted program
        timed(r, "static")

        # run 1: adaptive corrects at runtime, stats land in the journal
        r = fresh_runner(hbo="1", adaptive="1")
        out["run1_first_plan"] = _hbo_build_side(r, sql)
        r.execute(sql)  # warmup; also the first history-recorded execution
        out["run1_decisions"] = rt.queries()[-1].adaptive_decisions
        timed(r, "run1")

        # run 2: fresh runner, second-run planning — history must pick the
        # correct build side before a single row moves
        r = fresh_runner(hbo="1", adaptive="0")
        out["run2_plan"] = _hbo_build_side(r, sql)
        rep = last_report()
        if rep is not None:
            out["run2_planning_ms"] = round(rep.planning_ms, 2)
            out["run2_history_lookups"] = rep.history_lookups
            out["run2_history_hits"] = rep.history_hits
        r.execute(sql)  # warmup
        out["run2_decisions"] = rt.queries()[-1].adaptive_decisions
        timed(r, "run2")

        out["rows_identical"] = (rows["static"] == rows["run1"] ==
                                 rows["run2"])
        out["wall_ratio_run2_vs_run1"] = round(
            out["wall_s_run2"] / max(out["wall_s_run1"], 1e-9), 3)
        out["speedup_vs_static"] = round(
            out["wall_s_static"] / max(out["wall_s_run2"], 1e-9), 2)
        return out
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def run_hbo_bench(write: bool = True) -> dict:
    """``bench.py --hbo``: the history-based-optimization acceptance leg.

    Re-runs the BENCH_r13 wrong-side-broadcast mis-estimate with history
    in the loop: run 1 (adaptive on, empty history) plans the broadcast
    wrong and gets corrected at runtime while plan_stats are journaled;
    run 2 (HBO on, adaptive OFF) must plan the correct PARTITIONED build
    side up front from the recorded stats, with wall <= 1.15x the
    adaptive-on run-1 wall, identical rows, and planning-time overhead
    recorded from the iterative optimizer trace.

    Env knobs: BENCH_ADAPTIVE_SF (default 0.3), BENCH_ADAPTIVE_WORKERS
    (default 4), BENCH_ITERS (default 3).  Writes BENCH_r18.json."""
    sf = float(os.environ.get("BENCH_ADAPTIVE_SF", "0.3"))
    workers = int(os.environ.get("BENCH_ADAPTIVE_WORKERS", "4"))
    iters = int(os.environ.get("BENCH_ITERS", "3"))
    _ensure_backend()
    _enable_compile_cache()

    from trino_tpu.telemetry.metrics import REGISTRY

    print(f"hbo second-run: sf={sf:g} workers={workers}", file=sys.stderr)
    leg = _hbo_second_run(_ADAPTIVE_WRONG_SQL, sf, workers, iters)
    print(f"wrong_side_broadcast: {leg}", file=sys.stderr)

    # run2 may carry plan-time hbo_fanout tags, but every RUNTIME
    # correction (flip/skew-split) must be gone: history planned it right.
    # "correct build side up front" = the mis-estimated orders subquery is
    # no longer the broadcast build; with true stats the reorderer either
    # partitions it or puts the genuinely small customer side on build.
    runtime_fixes = ("flip_to" in leg["run2_decisions"]
                     or "skew_split" in leg["run2_decisions"])
    ok = (leg["rows_identical"]
          and leg["static_plan"] == {"distribution": "BROADCAST",
                                     "build_tables": ["orders"]}
          and leg["run1_first_plan"] == leg["static_plan"]
          and "flip_to_partitioned" in leg["run1_decisions"]
          and "orders" not in leg["run2_plan"]["build_tables"]
          and not runtime_fixes
          and leg["wall_ratio_run2_vs_run1"] <= 1.15)
    result = {
        "metric": f"hbo_second_run_wall_ratio_sf{sf:g}",
        "value": leg["wall_ratio_run2_vs_run1"],
        "unit": "run-2 wall (HBO=1, adaptive=0) / adaptive-on run-1 wall "
                "(target <= 1.15; run-2 must plan the build side right "
                "up front)",
        "workers": workers,
        "iters": iters,
        "wrong_side_broadcast": leg,
        "pass": ok,
        "metrics": {k: v for k, v in REGISTRY.snapshot().items()
                    if k.startswith(("trino_hbo", "trino_optimizer"))},
    }
    print(json.dumps(result))
    if write:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_r18.json"), "w") as f:
            json.dump(result, f, indent=1)
            f.write("\n")
    return result


def run_baseline() -> None:
    """CPU reference: same engine, same data, 8-worker DistributedQueryRunner.
    Runs in a subprocess with JAX_PLATFORMS=cpu (BASELINE.md config #1)."""
    sf = float(os.environ.get("BENCH_SF", "2"))
    workers = int(os.environ.get("BENCH_BASELINE_WORKERS", "8"))
    _enable_compile_cache()
    from trino_tpu.execution.distributed_runner import DistributedQueryRunner
    from trino_tpu.runner import Session

    catalog = _stage_memory_tables(sf)
    runner = DistributedQueryRunner(
        catalog, worker_count=workers,
        session=Session(default_catalog="memory", node_count=workers))
    times: dict[str, float] = {}
    for name, sql in QUERIES.items():
        runner.execute(sql)  # warmup
        t0 = time.perf_counter()
        runner.execute(sql)
        times[name] = time.perf_counter() - t0
    print(json.dumps(times))


def run_scan_bench() -> None:
    """`bench.py --scan`: the scan-ingest microbench.  Drains TPC-H lineitem
    through ScanOperator three ways and reports GB/s from the ScanIngestStats
    counters, so the ingest trajectory is tracked per round independently of
    the full-query bench:

    - ``legacy``:   the pre-PR synchronous path — string-materializing decode
                    (TRINO_TPU_TPCH_VECTOR_DECODE=0), no prefetch.  This is
                    the acceptance baseline.
    - ``sync``:     vectorized decode, synchronous scan (TRINO_TPU_PREFETCH=0).
    - ``prefetch``: vectorized decode + async prefetch/coalesce/staging.

    ``vs_baseline`` in the JSON is prefetch over legacy.  Note prefetch vs
    sync (``vs_sync``) only wins wall-clock when decode can overlap with
    something — on a single-core host with the now-cheap vectorized decode it
    hovers near 1.0; the ingest win lives in the decode itself and in
    transfer/compute overlap during real queries.

    Env knobs: BENCH_SCAN_SF (default 0.2), BENCH_SCAN_SPLITS (default 8),
    plus the TRINO_TPU_PREFETCH_* family."""
    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.exec.operators import ScanOperator

    sf = float(os.environ.get("BENCH_SCAN_SF", "0.2"))
    n_splits = int(os.environ.get("BENCH_SCAN_SPLITS", "8"))

    def drain(tpch) -> tuple[float, "object"]:
        cols = tpch.get_table_schema("lineitem").column_names()
        splits = tpch.get_splits("lineitem", n_splits, 1)
        scan = ScanOperator(tpch, splits, cols)
        t0 = time.perf_counter()
        while not scan.is_finished():
            if scan.get_output() is None:
                break
        return time.perf_counter() - t0, scan.ingest_stats

    results = {}
    for mode, prefetch, vector in (("legacy", "0", "0"), ("sync", "0", "1"),
                                   ("prefetch", "1", "1")):
        os.environ["TRINO_TPU_PREFETCH"] = prefetch
        os.environ["TRINO_TPU_TPCH_VECTOR_DECODE"] = vector
        # fresh connector per leg: the decode flag is read at construction
        tpch = default_catalog(scale_factor=sf).connector("tpch")
        drain(tpch)  # warmup: dictionaries + code tables + jit caches
        wall, stats = drain(tpch)
        gbps = stats.scan_bytes / wall / 1e9
        results[mode] = (wall, gbps, stats)
        print(f"scan[{mode}]: {stats.scan_bytes/1e6:.1f} MB in "
              f"{wall*1e3:.1f} ms = {gbps:.2f} GB/s | {stats.text()}",
              file=sys.stderr)
    os.environ.pop("TRINO_TPU_TPCH_VECTOR_DECODE", None)

    st = results["prefetch"][2]
    print(json.dumps({
        "metric": f"scan_ingest_sf{sf:g}_gb_per_sec",
        "value": round(results["prefetch"][1], 3),
        "unit": "GB/s",
        "vs_baseline": round(results["prefetch"][1] / results["legacy"][1], 3),
        "vs_sync": round(results["prefetch"][1] / results["sync"][1], 3),
        "legacy_gb_per_sec": round(results["legacy"][1], 3),
        "sync_gb_per_sec": round(results["sync"][1], 3),
        "queue_depth_max": st.queue_depth_max,
        "queue_depth_avg": round(st.queue_depth_avg, 2),
        "coalesced_batches": st.coalesced_batches,
        "source_read_ms": round(st.source_read_s * 1e3, 1),
        "consumer_wait_ms": round(st.consumer_wait_s * 1e3, 1),
        "stage_ms": round(st.stage_s * 1e3, 1),
    }))


def run_ndv_bench() -> None:
    """`bench.py --ndv [1e3,1e4,...]`: the hash-vs-sort NDV-ladder bake-off
    behind the ROADMAP "Pallas hash build/probe — or a measured waiver" item.

    For each NDV rung, times the two hottest inner loops under every
    TRINO_TPU_HASH_IMPL implementation:

    - ``agg``:  group-id assignment + one segment-sum over int64 keys
                (the HashAggregationOperator inner loop).
    - ``join``: hash-table build + probe-ranges + total fetch
                (the LookupJoin build/probe inner loop).

    Implementations: ``sort`` (lexsort + searchsorted), ``pallas-interpret``
    (the open-addressing kernels as pure XLA — runs anywhere, NOT a TPU
    performance number), and ``pallas`` (compiled kernels — requires a real
    TPU backend; recorded as ``"skipped"`` with rc 0 otherwise, same spirit
    as the subprocess device probe).  Keys are drawn from a SPARSE 62-bit
    domain so the sort leg cannot sneak onto the dense direct-address join
    fast path.  Emits ONE JSON object with per-leg rows/s + GB/s.

    Env knobs: BENCH_ITERS (default 3), BENCH_NDV_ROWS (default 1e6),
    BENCH_NDV_INTERPRET_ROWS (default 2e5 — interpret mode executes the
    probe loops sequentially and would dominate wall time at full width)."""
    _ensure_backend()
    _enable_compile_cache()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from trino_tpu.exec import join_exec as JX
    from trino_tpu.exec import kernels as K

    arg = ""
    i = sys.argv.index("--ndv")
    if i + 1 < len(sys.argv) and not sys.argv[i + 1].startswith("-"):
        arg = sys.argv[i + 1]
    ndvs = ([int(float(x)) for x in arg.split(",") if x]
            or [1_000, 10_000, 100_000, 1_000_000])

    iters = int(os.environ.get("BENCH_ITERS", "3"))
    full_rows = int(float(os.environ.get("BENCH_NDV_ROWS", "1e6")))
    interp_rows = int(float(os.environ.get("BENCH_NDV_INTERPRET_ROWS",
                                           "2e5")))
    on_tpu = jax.default_backend() == "tpu"
    impls = [
        ("sort", {"TRINO_TPU_HASH_IMPL": "sort"}, False),
        ("pallas-interpret",
         {"TRINO_TPU_HASH_IMPL": "pallas", "TRINO_TPU_HASH_INTERPRET": "1"},
         False),
        ("pallas", {"TRINO_TPU_HASH_IMPL": "pallas"}, True),  # needs TPU
    ]

    def timed(fn) -> float:
        fn()  # warmup: compile at this shape
        samples = []
        for _ in range(iters):
            t0 = time.perf_counter()
            fn()
            samples.append(time.perf_counter() - t0)
        samples.sort()
        return samples[len(samples) // 2]

    rng = np.random.default_rng(0)
    legs: list[dict] = []
    for ndv in ndvs:
        domain = rng.integers(0, 1 << 62, size=ndv, dtype=np.int64)
        for impl, env, needs_tpu in impls:
            n = interp_rows if impl == "pallas-interpret" else full_rows
            nb = max(n // 2, 1)
            if needs_tpu and not on_tpu:
                for leg in ("agg", "join"):
                    legs.append({"leg": leg, "impl": impl, "ndv": ndv,
                                 "status": "skipped",
                                 "reason": "no TPU backend"})
                continue
            for k in ("TRINO_TPU_HASH_IMPL", "TRINO_TPU_HASH_INTERPRET"):
                os.environ.pop(k, None)
            os.environ.update(env)
            jk = jnp.asarray(domain[rng.integers(0, ndv, size=n)])
            jv = jnp.asarray(rng.standard_normal(n))
            jbk = jnp.asarray(domain[rng.integers(0, ndv, size=nb)])
            jax.block_until_ready((jk, jv, jbk))

            def agg_leg():
                perm, gid, ng = K.group_ids_auto([(jk, None)], None)
                jax.block_until_ready(
                    jax.ops.segment_sum(jv[perm], gid, ng + 1))

            def join_leg():
                t = JX.build_table([(jbk, None)], num_rows=nb)
                _lo, _counts, total = JX.probe_ranges_device(
                    t, [(jk, None)], [None])
                total.get()

            for leg, fn, nbytes in (
                    ("agg", agg_leg, n * 16),
                    ("join", join_leg, (n + nb) * 8)):
                wall = timed(fn)
                row = {"leg": leg, "impl": impl, "ndv": ndv, "rows": n,
                       "wall_ms": round(wall * 1e3, 2),
                       "rows_per_s": round(n / wall),
                       "gb_per_s": round(nbytes / wall / 1e9, 3),
                       "status": "ok"}
                legs.append(row)
                print(f"ndv[{ndv}] {leg}/{impl}: {row['wall_ms']} ms = "
                      f"{row['rows_per_s']:,} rows/s", file=sys.stderr)
    for k in ("TRINO_TPU_HASH_IMPL", "TRINO_TPU_HASH_INTERPRET"):
        os.environ.pop(k, None)

    print(json.dumps({
        "metric": "hash_bakeoff_ndv",
        "unit": "rows/s",
        "backend": jax.default_backend(),
        "iters": iters,
        "legs": legs,
    }))


_JIT_COUNTER = {"on": False, "jit_calls": 0, "eager_binds": 0}
_REGION_TLS = None  # threading.local; armed per-thread so one task's stage
# region doesn't count another task's concurrent scan/feed launches


def _region_armed() -> bool:
    return _REGION_TLS is not None and getattr(_REGION_TLS, "depth", 0) > 0


def _install_jit_call_counter() -> None:
    """Count every Python->device dispatch: (a) wrap ``jax.jit`` so each call
    into a jitted callable is one program launch (installed BEFORE any
    trino_tpu import — module-level jitted kernels capture the wrapper at
    import time), and (b) patch ``jax.core.Primitive.bind`` so each EAGER op
    (the legacy flush path is lexsort/gather/segment-sum outside jit) counts
    too.  A cached jit call binds nothing (C++ fast path), so the two buckets
    don't double-count; trace-time binds are avoided by counting only
    pre-warmed runs.  This is the honest unit for "per-batch Python
    dispatch": each one is a Python->device launch, the thing that costs
    dispatch latency per batch on a real TPU."""
    import functools

    import jax

    orig_jit = jax.jit

    def counting_jit(fun=None, **kw):
        if fun is None:
            return lambda f: counting_jit(f, **kw)
        compiled = orig_jit(fun, **kw)

        @functools.wraps(fun)
        def dispatch(*a, **k):
            if _JIT_COUNTER["on"] or _region_armed():
                _JIT_COUNTER["jit_calls"] += 1
            return compiled(*a, **k)

        return dispatch

    jax.jit = counting_jit

    prim = jax.core.Primitive
    orig_bind = prim.bind

    def counting_bind(self, *a, **k):
        if _JIT_COUNTER["on"] or _region_armed():
            _JIT_COUNTER["eager_binds"] += 1
        return orig_bind(self, *a, **k)

    prim.bind = counting_bind


def _count_jit_dispatches(runner, sql: str) -> dict[str, int]:
    """One un-timed (pre-warmed) run with the dispatch counter armed: total
    Python->device launches (jitted-program calls + eager primitive binds)
    for the whole query.  The scan / feed side is identical in both legs, so
    including it only DILUTES the fused-vs-legacy ratio — the headline
    number is conservative."""
    _JIT_COUNTER["jit_calls"] = 0
    _JIT_COUNTER["eager_binds"] = 0
    _JIT_COUNTER["on"] = True
    try:
        runner.execute(sql)
    finally:
        _JIT_COUNTER["on"] = False
    return {"jit_calls": _JIT_COUNTER["jit_calls"],
            "eager_binds": _JIT_COUNTER["eager_binds"],
            "total": _JIT_COUNTER["jit_calls"] + _JIT_COUNTER["eager_binds"]}


def _count_stage_dispatches(runner, sql: str) -> tuple[dict[str, int], int]:
    """One un-timed (pre-warmed) run with the stage-region operators wrapped
    by counting shims.  Returns (operator-method counts, region device
    dispatches): every Python-level ``add_input``/``get_output`` crossing of
    the PARTIAL->shuffle->FINAL region is one operator dispatch, and the
    launch counter is armed ONLY while a region operator method is on the
    stack, so the region launch total excludes the scan/feed side that both
    legs share.  Filter/project is tallied but NEVER armed — the chain's
    filter/project work runs INSIDE the fused program (fully counted there)
    while the legacy leg's equivalent jit call is excluded, which biases the
    comparison AGAINST the fused path."""
    import threading

    import trino_tpu.exec.operators as O
    import trino_tpu.execution.collective_exchange as CE
    import trino_tpu.execution.plan_compiler as PC
    import trino_tpu.execution.stage_compiler as SC

    global _REGION_TLS
    _REGION_TLS = threading.local()
    tls = _REGION_TLS
    counts: dict[str, int] = {}
    targets = [
        (O.FilterProjectOperator, "add_input", "filter_project", False),
        (O.HashAggregationOperator, "add_input", "hash_agg", True),
        (O.HashAggregationOperator, "get_output", "hash_agg", True),
        (O.HashAggregationOperator, "finish_input", None, True),
        (CE.CollectiveOutputSink, "add_input", "exchange", True),
        (CE.CollectiveOutputSink, "finish_input", None, True),
        (CE.CollectiveSourceOperator, "get_output", "exchange", True),
        (SC.FusedStageSinkOperator, "add_input", "fused_sink", True),
        (SC.FusedStageSinkOperator, "finish_input", None, True),
        (SC.FusedStageSourceOperator, "get_output", "fused_source", True),
        (PC.ResidentPlanSinkOperator, "add_input", "resident_sink", True),
        (PC.ResidentPlanSinkOperator, "finish_input", None, True),
        (PC.ResidentBuildSinkOperator, "add_input", "resident_build", True),
        (PC.ResidentBuildSinkOperator, "finish_input", None, True),
    ]
    saved = []
    for cls, meth, label, arm in targets:
        orig = getattr(cls, meth)

        def shim(self, *a, _orig=orig, _label=label, _arm=arm, **k):
            if _label is not None:
                counts[_label] = counts.get(_label, 0) + 1
            if not _arm:
                return _orig(self, *a, **k)
            tls.depth = getattr(tls, "depth", 0) + 1
            try:
                return _orig(self, *a, **k)
            finally:
                tls.depth -= 1

        saved.append((cls, meth, orig))
        setattr(cls, meth, shim)
    _JIT_COUNTER["jit_calls"] = 0
    _JIT_COUNTER["eager_binds"] = 0
    try:
        runner.execute(sql)
    finally:
        _REGION_TLS = None
        for cls, meth, orig in saved:
            setattr(cls, meth, orig)
    region_launches = _JIT_COUNTER["jit_calls"] + _JIT_COUNTER["eager_binds"]
    return counts, region_launches


def run_fused_bench() -> None:
    """`bench.py --fused`: whole-query resident compilation vs whole-stage
    compilation vs the legacy per-operator + collective-exchange path
    (TRINO_TPU_RESIDENT_PLAN / TRINO_TPU_FUSED_STAGE) on the 8-device CPU
    mesh, plus a mesh-width scaling curve (1/2/4/8 host-platform devices)
    for the fully-resident q3.  Per query: median wall, input rows/s,
    program compile count + shape-bucket cache hit rate, and the per-batch
    Python dispatch counts of the stage region; results land in
    BENCH_r17.json.  Env knobs: BENCH_FUSED_SF (default 0.1),
    BENCH_FUSED_WORKERS (default 4), BENCH_ITERS (default 3)."""
    if os.environ.get("BENCH_FUSED_INNER") != "1":
        # the mesh needs --xla_force_host_platform_device_count before jax
        # imports; re-exec in a subprocess (same pattern as --baseline)
        base_xla = os.environ.get("XLA_FLAGS", "")

        def inner(n_dev: int, extra_env: dict) -> dict:
            xla = (base_xla
                   + f" --xla_force_host_platform_device_count={n_dev}"
                   ).strip()
            env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS=xla,
                       BENCH_FUSED_INNER="1", **extra_env)
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--fused"],
                env=env, capture_output=True, text=True, timeout=7200)
            if proc.stderr:
                print(proc.stderr[-4000:], file=sys.stderr)
            if proc.returncode != 0:
                raise SystemExit("fused bench inner run failed")
            return json.loads(proc.stdout.strip().splitlines()[-1])

        data = inner(8, {})
        # mesh-width scaling: one subprocess per width so the forced
        # host-platform device count (and the mesh it bounds) matches
        data["mesh_scaling"] = {
            str(w): inner(w, {"BENCH_FUSED_SCALE_WIDTH": str(w),
                              "BENCH_FUSED_WORKERS": str(w)})
            for w in (1, 2, 4, 8)}
        line = json.dumps(data)
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_r17.json")
        with open(path, "w") as f:
            f.write(line + "\n")
        print(line)
        return

    if os.environ.get("BENCH_FUSED_SCALE_WIDTH"):
        _run_fused_scale_leg()
        return

    sf = float(os.environ.get("BENCH_FUSED_SF", "0.1"))
    iters = int(os.environ.get("BENCH_ITERS", "3"))
    workers = int(os.environ.get("BENCH_FUSED_WORKERS", "4"))
    # the A/B re-executes identical statements: a served cached result
    # would measure the PR 12 result cache, not the execution legs
    os.environ["TRINO_TPU_RESULT_CACHE"] = "0"
    _enable_compile_cache()
    import jax

    _install_jit_call_counter()  # must precede the trino_tpu imports

    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.exec.stats import FusedStageStats, ResidentPlanStats
    from trino_tpu.execution.distributed_runner import DistributedQueryRunner
    from trino_tpu.execution.plan_compiler import ResidentPlanExec
    from trino_tpu.runner import Session

    # tpch connector directly (NOT the consolidated memory tables): the
    # per-batch dispatch story needs the natural multi-batch scan stream,
    # and both legs read the identical stream so the A/B stays fair
    catalog = default_catalog(scale_factor=sf)
    runner = DistributedQueryRunner(
        catalog, worker_count=workers, session=Session(node_count=workers))

    import trino_tpu.exec.operators as O

    # four legs: resident (whole-QUERY compilation — joins inlined), fused
    # (PR 6 whole-stage seam only), the default legacy path (which BUFFERS
    # a task's whole input and aggregates once — per-TASK amortization the
    # CPU mesh can afford), and the legacy path with a memory-bounded flush
    # window sized to the batch bucket (the streaming regime a device-
    # resident stage actually runs in: HBM cannot buffer a task's whole
    # input, so PARTIAL flushes per window — this is the per-batch dispatch
    # regime whole-stage/whole-query compilation eliminates)
    stream_flush = 1 << 15
    modes = (("resident", "auto", "auto", None),
             ("fused", "auto", "0", None),
             ("legacy", "0", "0", None),
             ("legacy_streaming", "0", "0", stream_flush))
    queries: dict[str, dict] = {}
    for name, sql in QUERIES.items():
        rows, _ = _scan_stats(runner, sql)
        per_mode: dict[str, dict] = {}
        for mode, env_val, resident_val, flush_rows in modes:
            os.environ["TRINO_TPU_FUSED_STAGE"] = env_val
            os.environ["TRINO_TPU_RESIDENT_PLAN"] = resident_val
            default_flush = O.HashAggregationOperator.FLUSH_ROWS
            if flush_rows is not None:
                O.HashAggregationOperator.FLUSH_ROWS = flush_rows
            try:
                runner.execute(sql)  # warmup: compile every program
                samples = []
                for _ in range(iters):
                    t0 = time.perf_counter()
                    runner.execute(sql)
                    samples.append(time.perf_counter() - t0)
                samples.sort()
                wall = samples[len(samples) // 2]
                counts, region_launches = _count_stage_dispatches(runner, sql)
                launches = _count_jit_dispatches(runner, sql)
            finally:
                O.HashAggregationOperator.FLUSH_ROWS = default_flush
            region = {k: v for k, v in counts.items()
                      if k != "filter_project"}
            entry = {
                "wall_ms": round(wall * 1e3, 1),
                "input_rows_per_sec": round(rows / wall),
                "region_device_dispatches": region_launches,
                "query_device_dispatches": launches["total"],
                "stage_dispatches": sum(region.values()),
                "dispatch_detail": counts,
            }
            if flush_rows is not None:
                entry["flush_rows"] = flush_rows
            if mode == "resident":
                rroll = ResidentPlanStats()
                for ex in runner._resident_edges.values():
                    if isinstance(ex, ResidentPlanExec):
                        rroll.merge(ex.rstats)
                entry["resident_plans"] = rroll.plans
                if rroll.plans:
                    # the whole point: the entire join tree + agg is ONE
                    # jitted dispatch per probe batch
                    entry.update({
                        "batches": rroll.batches,
                        "jit_calls": rroll.jit_calls,
                        "seams_fused": rroll.seams,
                        "seam_merges": rroll.merges,
                        "code_seam_columns": rroll.code_seam_columns,
                        "launches_per_batch": round(
                            rroll.launches_per_batch, 2),
                    })
            if mode == "fused":
                assert runner._fused_edges, \
                    f"{name}: expected a fused stage seam"
                roll = FusedStageStats()
                for ex in runner._fused_edges.values():
                    roll.merge(ex.stats)
                entry.update({
                    "batches": roll.batches,
                    "jit_calls": roll.jit_calls,
                    "compiles": roll.compiles,
                    "cache_hits": roll.cache_hits,
                    "cache_hit_rate": round(
                        roll.cache_hits / roll.jit_calls, 3)
                    if roll.jit_calls else 0.0,
                    "seam_merges": roll.merges,
                    # the whole point: ONE jitted call per input batch
                    "dispatches_per_batch": round(
                        (roll.jit_calls + roll.merges)
                        / max(roll.batches, 1), 2),
                })
            per_mode[mode] = entry
            print(f"{name}[{mode}]: {entry['wall_ms']} ms, "
                  f"{entry['input_rows_per_sec']:,} rows/s, "
                  f"{entry['stage_dispatches']} stage dispatches",
                  file=sys.stderr)
        os.environ.pop("TRINO_TPU_FUSED_STAGE", None)
        os.environ.pop("TRINO_TPU_RESIDENT_PLAN", None)
        fused = per_mode["fused"]
        batches = max(fused.get("batches", 1), 1)
        # per-batch normalization over the input batches the stage absorbed
        # (the batch stream is identical in every leg).  The region launch
        # count is armed only inside stage-region operator methods, with the
        # legacy chain's filter/project jit call EXCLUDED (it runs inside
        # the fused program, which is fully counted) — both choices bias
        # against the fused path, so the ratios are underestimates.
        res_batches = max(per_mode["resident"].get("batches", batches), 1)
        for m, b in (("resident", res_batches), ("fused", batches),
                     ("legacy", batches), ("legacy_streaming", batches)):
            per_mode[m]["region_dispatches_per_batch"] = round(
                per_mode[m]["region_device_dispatches"] / b, 2)
        fused_r = max(fused["region_device_dispatches"], 1)
        per_mode["dispatch_reduction"] = round(
            per_mode["legacy_streaming"]["region_device_dispatches"]
            / fused_r, 2)
        per_mode["dispatch_reduction_vs_buffered"] = round(
            per_mode["legacy"]["region_device_dispatches"] / fused_r, 2)
        if per_mode["resident"].get("resident_plans"):
            # the resident region ALSO covers the inlined joins, which the
            # other legs run un-armed on the operator pipeline — the ratio
            # still undercounts the resident win
            res_r = max(per_mode["resident"]["region_device_dispatches"], 1)
            per_mode["resident_dispatch_reduction"] = round(
                per_mode["legacy_streaming"]["region_device_dispatches"]
                / res_r, 2)
        queries[name] = per_mode

    print(json.dumps({
        "metric": f"resident_plan_sf{sf:g}",
        "backend": jax.default_backend(),
        "devices": len(jax.devices()),
        "workers": workers,
        "iters": iters,
        "queries": queries,
    }))


def _run_fused_scale_leg() -> None:
    """One mesh-width point of the scaling curve: q3 fully resident on a
    BENCH_FUSED_SCALE_WIDTH-task mesh (the forced host-platform device
    count matches, so the mesh is exactly that wide).  Width 1 has no
    collectives — the resident plan is ineligible there and the point
    records the serial baseline."""
    width = int(os.environ["BENCH_FUSED_SCALE_WIDTH"])
    sf = float(os.environ.get("BENCH_FUSED_SF", "0.1"))
    iters = int(os.environ.get("BENCH_ITERS", "3"))
    os.environ["TRINO_TPU_RESULT_CACHE"] = "0"
    _enable_compile_cache()
    import jax

    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.exec.stats import ResidentPlanStats
    from trino_tpu.execution.distributed_runner import DistributedQueryRunner
    from trino_tpu.execution.plan_compiler import ResidentPlanExec
    from trino_tpu.runner import Session

    os.environ["TRINO_TPU_RESIDENT_PLAN"] = "auto"
    catalog = default_catalog(scale_factor=sf)
    runner = DistributedQueryRunner(
        catalog, worker_count=width, session=Session(node_count=width))
    sql = QUERIES["q3"]
    rows, _ = _scan_stats(runner, sql)
    runner.execute(sql)  # warmup: compile every program for this width
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        runner.execute(sql)
        samples.append(time.perf_counter() - t0)
    samples.sort()
    wall = samples[len(samples) // 2]
    roll = ResidentPlanStats()
    for ex in runner._resident_edges.values():
        if isinstance(ex, ResidentPlanExec):
            roll.merge(ex.rstats)
    out = {
        "devices": len(jax.devices()),
        "workers": width,
        "wall_ms": round(wall * 1e3, 1),
        "input_rows_per_sec": round(rows / wall),
        "resident_plans": roll.plans,
    }
    if roll.plans:
        out.update({
            "batches": roll.batches,
            "jit_calls": roll.jit_calls,
            "launches_per_batch": round(roll.launches_per_batch, 2),
        })
    print(json.dumps(out))


def run_profile_bench() -> None:
    """``--profile``: run Q1 through the engine with the flight recorder on
    and dump the merged Chrome trace (open in Perfetto / chrome://tracing).
    BENCH_PROFILE_OUT sets the output path; BENCH_PROFILE_FULL=1 switches
    to TRINO_TPU_PROFILE=full device-time attribution."""
    sf = float(os.environ.get("BENCH_SF", "0.1"))
    out_path = os.environ.get("BENCH_PROFILE_OUT", "/tmp/trino_tpu_trace.json")
    _ensure_backend()
    _enable_compile_cache()

    from trino_tpu.runner import Session, StandaloneQueryRunner
    from trino_tpu.telemetry import profiler

    prev = None
    if os.environ.get("BENCH_PROFILE_FULL", "") == "1":
        prev = profiler.set_level(2)
    catalog = _stage_memory_tables(sf)
    runner = StandaloneQueryRunner(
        catalog, session=Session(default_catalog="memory", splits_per_node=1))
    runner.execute(Q1, query_id="bench_warm")  # warm compile caches
    t0 = time.perf_counter()
    runner.execute(Q1, query_id="bench_profile")
    wall_s = time.perf_counter() - t0
    trace = runner.profile("bench_profile")
    if prev is not None:
        profiler.set_level(prev)
    assert trace is not None, "profiler produced no trace"
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(trace, f)
    by_cat: dict[str, int] = {}
    for ev in trace["traceEvents"]:
        if ev.get("ph") == "X":
            by_cat[ev["cat"]] = by_cat.get(ev["cat"], 0) + 1
    print(json.dumps({
        "metric": f"profile_sf{sf:g}",
        "wall_ms": round(wall_s * 1e3, 1),
        "trace_path": out_path,
        "events": sum(by_cat.values()),
        "events_by_cat": by_cat,
        "full_mode": prev is not None,
    }))


# ----------------------------------------------------- compressed execution

ENCODED_QUERIES = {
    # every group key is a dictionary column: encoded execution group-bys on
    # int32 codes; the decode-off leg hashes materialized python strings
    # min/max over the wide-vocabulary comment column run as int32 code
    # comparisons (the connector's dictionaries are sorted, so code order IS
    # lexical order); the decode-off leg compares materialized strings
    "dict_groupby": """
select l_returnflag, l_linestatus, count(*), sum(l_quantity),
       min(l_comment), max(l_comment)
from lineitem group by l_returnflag, l_linestatus""",
    # low-selectivity filter over wide payload: the mask computes from
    # l_orderkey alone, payload columns stage LAZY and are dropped unread
    # for every batch with zero survivors.  The modulo keeps the predicate
    # out of the scan's advisory TupleDomain (planner/domains.py would push
    # a plain equality into the connector and prune the scan itself, which
    # benchmarks pushdown, not late materialization).
    "lazy_filter": """
select l_extendedprice, l_discount, l_tax, l_comment
from lineitem where l_orderkey % 1000000000 = 1""",
}


def run_encoded_leg() -> None:
    """``--encoded-leg``: one leg of the compressed-execution ladder, run in
    a fresh interpreter (TRINO_TPU_TPCH_VECTOR_DECODE is read at connector
    construction, so legs cannot share a process).  Prints one JSON object
    keyed by query with wall time, rows/s, and staged-bytes accounting from
    the trino_scan_* / trino_encoding_* registry deltas."""
    sf = float(os.environ.get("BENCH_SF", "0.2"))
    iters = int(os.environ.get("BENCH_ITERS", "3"))
    # measure execution, not the cache plane: a Tier C hit would serve the
    # repeat submissions without ever touching the encoded operators
    os.environ["TRINO_TPU_PLAN_CACHE"] = "0"
    os.environ["TRINO_TPU_RESULT_CACHE"] = "0"
    _ensure_backend()
    _enable_compile_cache()

    import jax

    import trino_tpu.exec.operators as ops
    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.runner import Session, StandaloneQueryRunner
    from trino_tpu.telemetry.metrics import REGISTRY

    # track the peak host-resident batch crossing a bucketing boundary
    # (LAZY columns count only once materialized — their bytes are exactly
    # what late materialization keeps off the device)
    peak = {"v": 0}
    orig_pad = ops.pad_to_bucket

    def pad_spy(batch):
        out = orig_pad(batch)
        resident = sum(c.nbytes for c in out.columns
                       if c.encoding != "LAZY" or c.is_materialized)
        peak["v"] = max(peak["v"], resident)
        return out

    ops.pad_to_bucket = pad_spy

    # many small splits -> many scan batches: late materialization drops
    # payload at batch granularity, so batch count is the lazy resolution
    splits = int(os.environ.get("BENCH_ENCODED_SPLITS", "32"))
    runner = StandaloneQueryRunner(
        default_catalog(scale_factor=sf),
        session=Session(splits_per_node=splits))

    def snap() -> dict:
        s = REGISTRY.snapshot()
        return {k: s[k]["value"] for k in (
            "trino_scan_bytes_total",
            "trino_encoding_bytes_saved_total",
            "trino_encoding_lazy_skipped_bytes_total",
            "trino_encoding_lazy_materialized_bytes_total",
            "trino_encoding_lazy_columns_total",
            "trino_encoding_lazy_materialized_total",
            "trino_encoding_rle_agg_rows_total",
        )}

    out: dict[str, dict] = {}
    for name, sql in ENCODED_QUERIES.items():
        input_rows, _ = _scan_stats(runner, sql)
        runner.execute(sql)  # warmup: compile every jitted program
        peak["v"] = 0
        before = snap()
        samples = []
        for _ in range(iters):
            t0 = time.perf_counter()
            r = runner.execute(sql)
            for c in r.batch.columns:
                jax.block_until_ready(c.data)
            samples.append(time.perf_counter() - t0)
        delta = {k: (v - before[k]) / iters for k, v in snap().items()}
        samples.sort()
        wall = samples[len(samples) // 2]
        scan_b = delta["trino_scan_bytes_total"]
        # deferred = bytes that never moved: RLE/dict shrinkage plus lazy
        # deferrals, minus the lazy thunks a surviving row forced to run
        deferred = (delta["trino_encoding_bytes_saved_total"]
                    + delta["trino_encoding_lazy_skipped_bytes_total"]
                    - delta["trino_encoding_lazy_materialized_bytes_total"])
        out[name] = {
            "wall_ms": round(wall * 1e3, 1),
            "input_rows_per_sec": round(input_rows / wall),
            "scan_bytes": round(scan_b),
            "staged_bytes": round(scan_b - deferred),
            "deferred_bytes": round(deferred),
            # payload view: bytes the filter COULD have skipped (all
            # lazy-staged columns) vs the part survivor batches forced in
            "lazy_payload_bytes": round(
                delta["trino_encoding_lazy_skipped_bytes_total"]),
            "lazy_payload_staged_bytes": round(
                delta["trino_encoding_lazy_materialized_bytes_total"]),
            "peak_batch_bytes": peak["v"],
            "lazy_columns": delta["trino_encoding_lazy_columns_total"],
            "lazy_materialized":
                delta["trino_encoding_lazy_materialized_total"],
            "rle_agg_rows": delta["trino_encoding_rle_agg_rows_total"],
        }
    print(json.dumps(out))


def run_encoded_bench() -> None:
    """``bench.py --encoded``: the compressed-execution ladder (PR 16).
    Three legs, each a fresh interpreter over the sf-scaled TPC-H connector:

    - **encoded** — TRINO_TPU_ENCODED_EXEC=1: dictionary codes, RLE runs and
      lazy payload columns flow end-to-end.
    - **legacy** — TRINO_TPU_ENCODED_EXEC=0: same vectorized connector, but
      every batch expands at the scan boundary (the bit-for-bit oracle leg).
    - **legacy_decode_off** — additionally TRINO_TPU_TPCH_VECTOR_DECODE=0:
      the string-materializing row decoder, i.e. execution with no
      dictionary anywhere (what a row-oriented engine would stage).

    Acceptance: >=2x rows/s on the dictionary-heavy group-by vs the decoded
    legacy, and the low-selectivity filter stages <10% of the payload bytes
    the legacy leg stages (>=5x staged-bytes reduction).  Writes
    BENCH_r16.json.  Env knobs: BENCH_SF (default 0.2), BENCH_ITERS (3)."""
    sf = float(os.environ.get("BENCH_SF", "0.2"))
    legs = {
        "encoded": {"TRINO_TPU_ENCODED_EXEC": "1"},
        "legacy": {"TRINO_TPU_ENCODED_EXEC": "0"},
        "legacy_decode_off": {"TRINO_TPU_ENCODED_EXEC": "0",
                              "TRINO_TPU_TPCH_VECTOR_DECODE": "0"},
    }
    results: dict[str, dict] = {}
    for leg, env_over in legs.items():
        env = dict(os.environ, **env_over)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--encoded-leg"],
            env=env, capture_output=True, text=True, timeout=7200)
        if proc.returncode != 0:
            raise SystemExit(
                f"encoded bench leg {leg!r} failed:\n{proc.stderr[-4000:]}")
        results[leg] = json.loads(proc.stdout.strip().splitlines()[-1])
        print(f"leg {leg}: " + ", ".join(
            f"{q} {r['wall_ms']}ms ({r['input_rows_per_sec']} rows/s, "
            f"{r['staged_bytes'] / 1e6:.2f} MB staged)"
            for q, r in results[leg].items()), file=sys.stderr)

    gb_enc = results["encoded"]["dict_groupby"]
    gb_leg = results["legacy"]["dict_groupby"]
    gb_str = results["legacy_decode_off"]["dict_groupby"]
    lf_enc = results["encoded"]["lazy_filter"]
    lf_leg = results["legacy"]["lazy_filter"]
    # the legacy leg stages every payload byte; encoded stages only the
    # columns of batches that had a surviving row
    payload = max(lf_enc["lazy_payload_bytes"], 1)
    payload_staged = lf_enc["lazy_payload_staged_bytes"]
    staged_frac = payload_staged / payload
    summary = {
        "dict_groupby_speedup_vs_legacy": round(
            gb_enc["input_rows_per_sec"] / gb_leg["input_rows_per_sec"], 2),
        "dict_groupby_speedup_vs_decode_legacy": round(
            gb_enc["input_rows_per_sec"] / gb_str["input_rows_per_sec"], 2),
        "lazy_filter_payload_staged_fraction": round(staged_frac, 4),
        "lazy_filter_staged_bytes_reduction": round(1 / max(
            staged_frac, 1e-9), 1),
        "lazy_filter_total_staged_vs_legacy": round(
            lf_enc["staged_bytes"] / max(lf_leg["staged_bytes"], 1), 4),
        "lazy_filter_peak_batch_reduction": round(
            lf_leg["peak_batch_bytes"] / max(lf_enc["peak_batch_bytes"], 1),
            2),
    }
    result = {
        "metric": f"encoded_exec_sf{sf:g}",
        "iters": int(os.environ.get("BENCH_ITERS", "3")),
        "legs": results,
        "summary": summary,
        "acceptance": {
            "dict_groupby_2x": summary[
                "dict_groupby_speedup_vs_decode_legacy"] >= 2.0,
            "lazy_filter_staged_under_10pct": staged_frac < 0.10,
            "lazy_filter_5x_reduction": summary[
                "lazy_filter_staged_bytes_reduction"] >= 5.0,
        },
    }
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_r16.json"), "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    print(json.dumps(result))


def main() -> None:
    if "--baseline" in sys.argv:
        run_baseline()
        return
    if "--profile" in sys.argv:
        run_profile_bench()
        return
    if "--scan" in sys.argv:
        run_scan_bench()
        return
    if "--ndv" in sys.argv:
        run_ndv_bench()
        return
    if "--fused" in sys.argv:
        run_fused_bench()
        return
    if "--qps" in sys.argv:
        run_qps_bench()
        return
    if "--chaos-fte" in sys.argv:
        run_fte_chaos_bench()
        return
    if "--ha" in sys.argv:
        run_ha_bench()
        return
    if "--chaos" in sys.argv:
        run_chaos_bench()
        run_fte_chaos_bench()
        return
    if "--warm" in sys.argv:
        run_warm_bench()
        return
    if "--adaptive" in sys.argv:
        run_adaptive_bench()
        return
    if "--hbo" in sys.argv:
        run_hbo_bench()
        return
    if "--encoded-leg" in sys.argv:
        run_encoded_leg()
        return
    if "--encoded" in sys.argv:
        run_encoded_bench()
        return

    sf = float(os.environ.get("BENCH_SF", "2"))
    iters = int(os.environ.get("BENCH_ITERS", "3"))
    _ensure_backend()
    _enable_compile_cache()

    import jax

    from trino_tpu.exec import syncguard
    from trino_tpu.runner import Session, StandaloneQueryRunner

    catalog = _stage_memory_tables(sf)
    runner = StandaloneQueryRunner(
        catalog, session=Session(default_catalog="memory", splits_per_node=1))

    sync_before = syncguard.snapshot()
    times = _time_queries(runner, iters)
    sync = syncguard.take_delta(sync_before)
    chips = len(jax.devices()) if jax.default_backend() != "cpu" else 1
    per_query: dict[str, dict] = {}
    total_rows = total_bytes = 0.0
    for name, sql in QUERIES.items():
        r, b = _scan_stats(runner, sql)
        total_rows += r
        total_bytes += b
        per_query[name] = {
            "wall_ms": round(times[name] * 1e3, 1),
            "input_rows_per_sec": round(r / times[name]),
            "input_rows_per_sec_per_chip": round(r / times[name] / chips),
            "scan_gb_per_sec": round(b / times[name] / 1e9, 3),
        }
    total_time = sum(times.values())
    rows_per_sec = total_rows / total_time
    bytes_per_sec = total_bytes / total_time

    sane = bytes_per_sec <= HBM_PEAK_BYTES_PER_SEC
    print(
        f"sanity: scanned {total_bytes/1e6:.1f} MB in {total_time*1e3:.1f} ms "
        f"= {bytes_per_sec/1e9:.2f} GB/s vs HBM peak "
        f"{HBM_PEAK_BYTES_PER_SEC/1e9:.0f} GB/s -> "
        f"{'OK' if sane else 'EXCEEDS HARDWARE — MEASUREMENT REJECTED'}",
        file=sys.stderr)
    if not sane:
        raise SystemExit("bench measurement exceeds hardware bandwidth")

    vs_baseline = 0.0
    if os.environ.get("BENCH_SKIP_BASELINE", "0") != "1":
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--baseline"],
            env=env, capture_output=True, text=True, timeout=7200)
        if proc.returncode == 0:
            base = json.loads(proc.stdout.strip().splitlines()[-1])
            base_total = sum(base[q] for q in QUERIES)
            vs_baseline = base_total / total_time
            print(f"baseline (engine on {os.environ.get('BENCH_BASELINE_WORKERS', '8')}"
                  f"-worker CPU): {base} -> speedup {vs_baseline:.2f}x",
                  file=sys.stderr)
        else:
            print(f"baseline failed:\n{proc.stderr[-2000:]}", file=sys.stderr)

    from trino_tpu.telemetry.metrics import REGISTRY

    result = {
        "metric": f"tpch_q1_q3_engine_sf{sf:g}_input_rows_per_sec",
        "value": round(rows_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(vs_baseline, 3),
        "chips": chips,
        "per_query_ms": {q: round(t * 1e3, 1) for q, t in times.items()},
        "per_query": per_query,
        "scan_gb_per_sec": round(bytes_per_sec / 1e9, 3),
        "input_rows_per_sec_per_chip": round(rows_per_sec / chips),
        # host-transfer counters over the timed region (exec/syncguard.py):
        # the sync-free contract makes these flat in batch count
        "host_syncs": sync.host_syncs,
        "blocking_syncs": sync.blocking_syncs,
        "hot_loop_syncs": sync.hot_loop_syncs,
        "expand_overflows": sync.expand_overflows,
        # full process-wide metrics registry (telemetry/metrics.py): the
        # same snapshot /v1/metrics serves, archived with the bench run
        "metrics": REGISTRY.snapshot(),
    }
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_r07.json"), "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
